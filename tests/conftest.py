"""Test harness: force the CPU backend with 8 virtual devices.

This is the standard JAX way to test pjit/psum/mesh logic without a real pod
(SURVEY.md §4): multi-chip sharding tests see an 8-device mesh backed by host
CPU. Must run before any backend init; the pinning itself (env var + config
update, because the TPU plugin rewrites ``jax_platforms`` at interpreter
start) lives in :mod:`qdml_tpu.utils.platform`.
"""

from qdml_tpu.utils.platform import force_cpu

force_cpu(8)

# Persistent compilation cache: the suite is dominated by XLA CPU compiles of
# the same jitted steps across test files; caching them on disk makes repeat
# runs fast without changing any test semantics.
from qdml_tpu.utils.compile_cache import enable_compile_cache  # noqa: E402

enable_compile_cache()
