"""Training: end-to-end tiny runs, optimizer semantics, checkpoints."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax

from qdml_tpu.config import DataConfig, ExperimentConfig, ModelConfig, TrainConfig, override
from qdml_tpu.ops import gradient_prune
from qdml_tpu.train import (
    lr_schedule,
    restore_checkpoint,
    train_classifier,
    train_hdce,
)


def tiny_cfg(**train_overrides) -> ExperimentConfig:
    # reduced channel geometry (model dims derive from it) keeps the suite in
    # its wall-clock budget on the 1-CPU host (VERDICT r1 #7); full geometry
    # is covered by the science run and the data-contract tests
    cfg = ExperimentConfig(
        data=DataConfig(n_ant=16, n_sub=8, n_beam=4, data_len=80),
        model=ModelConfig(features=16),
        train=TrainConfig(batch_size=16, n_epochs=2, print_freq=1000),
    )
    for k, v in train_overrides.items():
        cfg = override(cfg, k, v)
    return cfg


def test_hdce_trains_and_improves():
    cfg = tiny_cfg()
    state, hist = train_hdce(cfg)
    assert len(hist["train_loss"]) == 2
    assert np.isfinite(hist["train_loss"]).all()
    # loss must drop substantially from the first epoch
    assert hist["train_loss"][1] < hist["train_loss"][0]
    # sanity bound only: with 8 total steps and BN still warming up, the
    # val NMSE vs the NOISY label (irreducible floor ~= label_noise_var) can
    # sit slightly above 1.0; real convergence is covered by the science run
    # (results/) and tests/test_bn_semantics.py.
    assert hist["val_nmse"][-1] < 1.5


def test_classical_classifier_trains():
    cfg = tiny_cfg()
    state, hist = train_classifier(cfg, quantum=False)
    assert hist["train_loss"][-1] < hist["train_loss"][0]
    assert hist["val_acc"][-1] > 0.34  # better than chance


def test_quantum_classifier_trains():
    cfg = tiny_cfg(**{"quantum.n_qubits": 4, "quantum.n_layers": 2})
    state, hist = train_classifier(cfg, quantum=True)
    assert np.isfinite(hist["train_loss"]).all()
    assert hist["train_loss"][-1] < hist["train_loss"][0]


def test_quantum_classifier_with_nat_and_pruning():
    cfg = tiny_cfg(
        **{
            "quantum.n_qubits": 4,
            "quantum.n_layers": 2,
            "quantum.use_quantumnat": True,
            "quantum.use_gradient_pruning": True,
            "quantum.gradient_threshold": 1e-6,
        }
    )
    state, hist = train_classifier(cfg, quantum=True)
    assert np.isfinite(hist["train_loss"]).all()


def test_gradient_prune_transform():
    tx = gradient_prune(threshold=0.5)
    params = {"w": jnp.zeros((4,))}
    st = tx.init(params)
    grads = {"w": jnp.asarray([0.1, -0.9, 0.6, -0.2])}
    out, st = tx.update(grads, st, params)
    np.testing.assert_allclose(np.asarray(out["w"]), [0.0, -0.9, 0.6, 0.0])
    np.testing.assert_allclose(float(st.prune_ratio), 0.5)


def test_gradient_prune_all_pruned_freezes_params():
    tx = optax.chain(gradient_prune(threshold=100.0), optax.adam(1e-3))
    params = {"w": jnp.ones((3,))}
    st = tx.init(params)
    updates, st = tx.update({"w": jnp.asarray([0.1, 0.2, 0.3])}, st, params)
    np.testing.assert_allclose(np.asarray(updates["w"]), 0.0, atol=1e-9)


def test_lr_schedule_reference_semantics():
    cfg = TrainConfig(lr=1e-3, lr_decay_epochs=30, lr_floor=1e-6)
    sched = lr_schedule(cfg, steps_per_epoch=10)
    np.testing.assert_allclose(float(sched(0)), 1e-3, rtol=1e-6)
    np.testing.assert_allclose(float(sched(29 * 10)), 1e-3, rtol=1e-6)
    np.testing.assert_allclose(float(sched(30 * 10)), 5e-4, rtol=1e-6)
    np.testing.assert_allclose(float(sched(60 * 10)), 2.5e-4, rtol=1e-6)
    np.testing.assert_allclose(float(sched(40 * 300 * 10)), 1e-6, rtol=1e-6)  # floor


def test_checkpoint_best_last_and_restore(tmp_path):
    cfg = tiny_cfg()
    state, hist = train_hdce(cfg, workdir=str(tmp_path))
    restored, meta = restore_checkpoint(str(tmp_path), "hdce_last")
    assert meta["epoch"] == 1
    got = jax.tree.leaves(restored["params"])
    want = jax.tree.leaves(jax.device_get(state.params))
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w), rtol=1e-6)
    assert (tmp_path / "hdce_best").is_dir()


def test_hdce_bf16_activation_path():
    """ModelConfig.dtype='bfloat16' runs the MXU fast path; params stay f32."""
    import jax

    from qdml_tpu.config import DataConfig, ExperimentConfig, ModelConfig, TrainConfig
    from qdml_tpu.data.datasets import DMLGridLoader
    from qdml_tpu.train.hdce import init_hdce_state, make_hdce_train_step

    cfg = ExperimentConfig(
        data=DataConfig(n_ant=16, n_sub=8, n_beam=4, data_len=64),
        model=ModelConfig(features=16, dtype="bfloat16"),
        train=TrainConfig(batch_size=8, n_epochs=1),
    )
    loader = DMLGridLoader(cfg.data, 8)
    batch = next(iter(loader.epoch(0)))
    model, state = init_hdce_state(cfg, loader.steps_per_epoch)
    step = make_hdce_train_step(model, state.tx)
    state, m = step(state, batch)
    assert float(m["loss"]) > 0 and float(m["loss"]) < 1e4
    assert all(l.dtype == "float32" for l in jax.tree.leaves(state.params))
