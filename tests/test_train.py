"""Training: end-to-end tiny runs, optimizer semantics, checkpoints."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from qdml_tpu.config import DataConfig, ExperimentConfig, ModelConfig, TrainConfig, override
from qdml_tpu.ops import gradient_prune
from qdml_tpu.train import (
    lr_schedule,
    restore_checkpoint,
    train_classifier,
    train_hdce,
)


def tiny_cfg(**train_overrides) -> ExperimentConfig:
    # reduced channel geometry (model dims derive from it) keeps the suite in
    # its wall-clock budget on the 1-CPU host (VERDICT r1 #7); full geometry
    # is covered by the science run and the data-contract tests
    cfg = ExperimentConfig(
        data=DataConfig(n_ant=16, n_sub=8, n_beam=4, data_len=80),
        model=ModelConfig(features=16),
        train=TrainConfig(batch_size=16, n_epochs=2, print_freq=1000),
    )
    for k, v in train_overrides.items():
        cfg = override(cfg, k, v)
    return cfg


def test_hdce_trains_and_improves():
    cfg = tiny_cfg()
    state, hist = train_hdce(cfg)
    assert len(hist["train_loss"]) == 2
    assert np.isfinite(hist["train_loss"]).all()
    # loss must drop substantially from the first epoch
    assert hist["train_loss"][1] < hist["train_loss"][0]
    # sanity bound only: with 8 total steps and BN still warming up, the
    # val NMSE vs the NOISY label (irreducible floor ~= label_noise_var) can
    # sit slightly above 1.0; real convergence is covered by the science run
    # (results/) and tests/test_bn_semantics.py.
    assert hist["val_nmse"][-1] < 1.5


def test_classical_classifier_trains():
    cfg = tiny_cfg()
    state, hist = train_classifier(cfg, quantum=False)
    assert hist["train_loss"][-1] < hist["train_loss"][0]
    assert hist["val_acc"][-1] > 0.34  # better than chance


def test_quantum_classifier_trains():
    cfg = tiny_cfg(**{"quantum.n_qubits": 4, "quantum.n_layers": 2})
    state, hist = train_classifier(cfg, quantum=True)
    assert np.isfinite(hist["train_loss"]).all()
    assert hist["train_loss"][-1] < hist["train_loss"][0]


def test_quantum_classifier_with_nat_and_pruning():
    cfg = tiny_cfg(
        **{
            "quantum.n_qubits": 4,
            "quantum.n_layers": 2,
            "quantum.use_quantumnat": True,
            "quantum.use_gradient_pruning": True,
            "quantum.gradient_threshold": 1e-6,
        }
    )
    state, hist = train_classifier(cfg, quantum=True)
    assert np.isfinite(hist["train_loss"]).all()


def test_gradient_prune_transform():
    tx = gradient_prune(threshold=0.5)
    params = {"w": jnp.zeros((4,))}
    st = tx.init(params)
    grads = {"w": jnp.asarray([0.1, -0.9, 0.6, -0.2])}
    out, st = tx.update(grads, st, params)
    np.testing.assert_allclose(np.asarray(out["w"]), [0.0, -0.9, 0.6, 0.0])
    np.testing.assert_allclose(float(st.prune_ratio), 0.5)


def test_gradient_prune_quantile_mode():
    """Quantile mode prunes a FRACTION of elements (scale-free): threshold
    0.5 zeroes the smallest half across the whole tree regardless of the
    gradients' absolute scale — the usable on-chip-QNN form (the reference's
    absolute 0.1 freezes Adam-scale training, results/noise_robustness/)."""
    tx = gradient_prune(threshold=0.5, mode="quantile")
    params = {"a": jnp.zeros((2,)), "b": jnp.zeros((2,))}
    st = tx.init(params)
    # tiny absolute scale: absolute-0.1 would zero ALL of these
    grads = {"a": jnp.asarray([1e-5, -9e-4]), "b": jnp.asarray([6e-4, -2e-5])}
    out, st = tx.update(grads, st, params)
    np.testing.assert_allclose(np.asarray(out["a"]), [0.0, -9e-4])
    np.testing.assert_allclose(np.asarray(out["b"]), [6e-4, 0.0])
    np.testing.assert_allclose(float(st.prune_ratio), 0.5)
    # boundary: threshold=0 is a no-op (cutoff = min |g|, inclusive keep)
    tx0 = gradient_prune(threshold=0.0, mode="quantile")
    out0, st0 = tx0.update(grads, tx0.init(params), params)
    np.testing.assert_allclose(np.asarray(out0["a"]), np.asarray(grads["a"]))
    np.testing.assert_allclose(float(st0.prune_ratio), 0.0)
    # boundary: all-equal magnitudes must never fully prune (cutoff ties keep)
    eq = {"a": jnp.full((4,), 1e-3)}
    txe = gradient_prune(threshold=0.5, mode="quantile")
    oute, ste = txe.update(eq, txe.init(eq), eq)
    np.testing.assert_allclose(np.asarray(oute["a"]), np.asarray(eq["a"]))
    with pytest.raises(ValueError, match="quantile threshold"):
        gradient_prune(threshold=1.5, mode="quantile")
    with pytest.raises(ValueError, match="mode"):
        gradient_prune(mode="topk")


def test_gradient_prune_all_pruned_freezes_params():
    tx = optax.chain(gradient_prune(threshold=100.0), optax.adam(1e-3))
    params = {"w": jnp.ones((3,))}
    st = tx.init(params)
    updates, st = tx.update({"w": jnp.asarray([0.1, 0.2, 0.3])}, st, params)
    np.testing.assert_allclose(np.asarray(updates["w"]), 0.0, atol=1e-9)


def test_adam_lowp_matches_f32():
    """scale_by_adam_lowp == optax f32 Adam to bf16 rounding of the carried
    first moment: same update directions over several steps on a real param
    tree; mu is stored bfloat16 (the HBM-traffic saving) while nu stays f32
    (its 1e-3/step EMA decay is below the bf16 half-ulp and would freeze —
    ADVICE r5 medium, observed in test_adam_lowp_nu_tracks_decaying_gradients)."""
    from qdml_tpu.train.optim import scale_by_adam_lowp

    rng = np.random.default_rng(3)
    params = {
        "w": jnp.asarray(rng.standard_normal((32, 16)).astype(np.float32)),
        "b": jnp.asarray(rng.standard_normal((16,)).astype(np.float32)),
    }
    ref = optax.scale_by_adam()
    low = scale_by_adam_lowp()
    s_ref, s_low = ref.init(params), low.init(params)
    assert s_low.mu["w"].dtype == jnp.bfloat16 and s_low.nu["b"].dtype == jnp.float32
    for step in range(5):
        grads = jax.tree_util.tree_map(
            lambda p: jnp.asarray(
                rng.standard_normal(p.shape).astype(np.float32) * 0.1
            ),
            params,
        )
        u_ref, s_ref = ref.update(grads, s_ref)
        u_low, s_low = low.update(grads, s_low)
        for k in params:
            a, b = np.asarray(u_ref[k]), np.asarray(u_low[k])
            # bf16 has ~3 decimal digits; updates are O(1) after Adam's
            # normalisation, so absolute agreement at ~1e-2 is the contract.
            np.testing.assert_allclose(a, b, atol=2e-2)


def test_adam_lowp_nu_tracks_decaying_gradients():
    """Long-horizon observation of the nu-freeze fix (ADVICE r5 medium): after
    a gradient spike followed by 1500 small-gradient steps, the second moment
    must DECAY toward the small steady state like f32 Adam's. The old
    bf16-stored nu could not (per-step relative change (1-b2)=1e-3 is below
    the bf16 half-ulp ~4e-3, so the EMA rounded back to itself every step and
    stayed pinned ~3x high, suppressing the effective step size)."""
    from qdml_tpu.train.optim import scale_by_adam_lowp

    n_steps, dim = 1500, 64
    params = {"w": jnp.zeros((dim,))}
    ref, low = optax.scale_by_adam(), scale_by_adam_lowp()
    # one spike step (|g|=1), then a long tail of small gradients (|g|=0.01)
    grads = jnp.concatenate(
        [jnp.ones((1, dim)), jnp.full((n_steps, dim), 0.01)], axis=0
    )

    def run(tx):
        def body(s, g):
            u, s = tx.update({"w": g}, s)
            return s, u["w"]

        return jax.jit(lambda s0: jax.lax.scan(body, s0, grads))(tx.init(params))

    s_ref, us_ref = run(ref)
    s_low, us_low = run(low)
    nu_ref = np.asarray(s_ref.nu["w"], np.float32)
    nu_low = np.asarray(s_low.nu["w"], np.float32)
    # f32 nu decays well below the post-spike value of ~1e-3...
    assert nu_ref.mean() < 5e-4
    # ...and the low-precision-moments optimizer tracks it (frozen bf16 nu
    # sat ~3x above), so the final update directions agree too
    np.testing.assert_allclose(nu_low, nu_ref, rtol=1e-2)
    np.testing.assert_allclose(
        np.asarray(us_low[-1]), np.asarray(us_ref[-1]), atol=2e-2
    )


def test_hdce_trains_with_bf16_moments():
    """End-to-end: moments_dtype="bfloat16" trains and improves like f32."""
    cfg = tiny_cfg(**{"train.moments_dtype": "bfloat16"})
    state, hist = train_hdce(cfg)
    assert np.isfinite(hist["train_loss"]).all()
    assert hist["train_loss"][1] < hist["train_loss"][0]


def test_bf16_moments_audit_across_all_four_step_makers():
    """moments_dtype='bfloat16' end-to-end audit (the donate/bf16 audit half
    graftlint can't check statically): the Adam trainers (HDCE, DCE) carry
    bf16 mu / f32 nu in their built optimizer state; the AdamW trainers (QSC
    and the NAT sweep force adamw per the reference) warn that the knob does
    not apply and keep f32 moments — never a silent three-of-four rollout."""
    import optax

    from qdml_tpu.train.dce import init_dce_state
    from qdml_tpu.train.hdce import init_hdce_state
    from qdml_tpu.train.nat_sweep import init_sweep
    from qdml_tpu.train.qsc import init_sc_state

    cfg = tiny_cfg(**{"train.moments_dtype": "bfloat16"})

    def adam_states(s):
        if isinstance(s, optax.ScaleByAdamState):
            yield s
        elif isinstance(s, (tuple, list)):
            for x in s:
                yield from adam_states(x)

    for init in (init_hdce_state, init_dce_state):
        _, state = init(cfg, steps_per_epoch=4)
        adams = list(adam_states(state.opt_state))
        assert adams, f"{init.__name__}: no Adam state found"
        for a in adams:
            assert all(l.dtype == jnp.bfloat16 for l in jax.tree.leaves(a.mu))
            assert all(l.dtype == jnp.float32 for l in jax.tree.leaves(a.nu))

    with pytest.warns(UserWarning, match="moments_dtype"):
        _, state = init_sc_state(cfg, quantum=True, steps_per_epoch=4)
    for a in adam_states(state.opt_state):
        assert all(l.dtype == jnp.float32 for l in jax.tree.leaves(a.mu))

    with pytest.warns(UserWarning, match="moments_dtype"):
        _, _, _, opt_state, _ = init_sweep(cfg, (0.0, 0.05), steps_per_epoch=4)
    for a in adam_states(opt_state):
        assert all(l.dtype == jnp.float32 for l in jax.tree.leaves(a.mu))


def test_lr_schedule_reference_semantics():
    cfg = TrainConfig(lr=1e-3, lr_decay_epochs=30, lr_floor=1e-6)
    sched = lr_schedule(cfg, steps_per_epoch=10)
    np.testing.assert_allclose(float(sched(0)), 1e-3, rtol=1e-6)
    np.testing.assert_allclose(float(sched(29 * 10)), 1e-3, rtol=1e-6)
    np.testing.assert_allclose(float(sched(30 * 10)), 5e-4, rtol=1e-6)
    np.testing.assert_allclose(float(sched(60 * 10)), 2.5e-4, rtol=1e-6)
    np.testing.assert_allclose(float(sched(40 * 300 * 10)), 1e-6, rtol=1e-6)  # floor


def test_checkpoint_best_last_and_restore(tmp_path):
    cfg = tiny_cfg()
    state, hist = train_hdce(cfg, workdir=str(tmp_path))
    restored, meta = restore_checkpoint(str(tmp_path), "hdce_last")
    assert meta["epoch"] == 1
    got = jax.tree.leaves(restored["params"])
    want = jax.tree.leaves(jax.device_get(state.params))
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w), rtol=1e-6)
    assert (tmp_path / "hdce_best").is_dir()


def test_hdce_bf16_activation_path():
    """ModelConfig.dtype='bfloat16' runs the MXU fast path; params stay f32."""
    import jax

    from qdml_tpu.config import DataConfig, ExperimentConfig, ModelConfig, TrainConfig
    from qdml_tpu.data.datasets import DMLGridLoader
    from qdml_tpu.train.hdce import init_hdce_state, make_hdce_train_step

    cfg = ExperimentConfig(
        data=DataConfig(n_ant=16, n_sub=8, n_beam=4, data_len=64),
        model=ModelConfig(features=16, dtype="bfloat16"),
        train=TrainConfig(batch_size=8, n_epochs=1),
    )
    loader = DMLGridLoader(cfg.data, 8)
    batch = next(iter(loader.epoch(0)))
    model, state = init_hdce_state(cfg, loader.steps_per_epoch)
    step = make_hdce_train_step(model, state.tx)
    state, m = step(state, batch)
    assert float(m["loss"]) > 0 and float(m["loss"]) < 1e4
    assert all(l.dtype == "float32" for l in jax.tree.leaves(state.params))


def test_scan_fused_steps_match_per_step_dispatch():
    """K scan-fused steps == K individual dispatches: same per-step losses and
    the same final parameters (the scan body inlines the SAME _fused_step and
    the SAME jitted batch generator, so the update sequence is identical)."""
    from qdml_tpu.data.channels import ChannelGeometry
    from qdml_tpu.data.datasets import DMLGridLoader
    from qdml_tpu.train.hdce import (
        init_hdce_state,
        make_hdce_scan_steps,
        make_hdce_train_step,
    )

    cfg = tiny_cfg(**{"data.snr_jitter": (5.0, 15.0)})  # per-step SNRs differ
    geom = ChannelGeometry.from_config(cfg.data)
    loader = DMLGridLoader(cfg.data, cfg.train.batch_size, "train", geom)
    assert loader.steps_per_epoch >= 3

    model, state_a = init_hdce_state(cfg, loader.steps_per_epoch)
    _, state_b = init_hdce_state(cfg, loader.steps_per_epoch)
    step = make_hdce_train_step(model, state_a.tx)
    losses_a = []
    for batch in loader.epoch(0):
        state_a, m = step(state_a, batch)
        losses_a.append(float(m["loss"]))

    run = make_hdce_scan_steps(model, geom)
    scen, user = loader.grid_coords
    losses_b = []
    for idx, snrs in loader.epoch_chunks(0, k=2):
        state_b, ms = run(state_b, jnp.uint32(cfg.data.seed), scen, user, idx, snrs)
        losses_b.extend(float(v) for v in ms["loss"])

    np.testing.assert_allclose(losses_a, losses_b, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(state_a.params), jax.tree.leaves(state_b.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6)


def test_train_hdce_scan_steps_config_path():
    """train_hdce with scan_steps>1 produces the same history as scan_steps=1."""
    hist1 = train_hdce(tiny_cfg())[1]
    hist2 = train_hdce(tiny_cfg(**{"train.scan_steps": 3}))[1]  # 5 steps/epoch -> 3+2 tail
    np.testing.assert_allclose(hist1["train_loss"], hist2["train_loss"], rtol=1e-5)
    np.testing.assert_allclose(hist1["val_nmse"], hist2["val_nmse"], rtol=1e-5)


def test_sc_scan_fused_matches_per_step_dispatch():
    """Classifier scan path == per-step dispatch, including the QuantumNAT
    noise stream (pre-split per-step keys reproduce the loop's split order)."""
    from qdml_tpu.config import QuantumConfig
    from qdml_tpu.data.channels import ChannelGeometry
    from qdml_tpu.data.datasets import DMLGridLoader
    from qdml_tpu.train.qsc import init_sc_state, make_sc_scan_steps, make_sc_train_step

    cfg = tiny_cfg()
    cfg = dataclasses.replace(
        cfg, quantum=QuantumConfig(n_qubits=4, n_layers=1, use_quantumnat=True)
    )
    geom = ChannelGeometry.from_config(cfg.data)
    loader = DMLGridLoader(cfg.data, cfg.train.batch_size, "train", geom)

    model, state_a = init_sc_state(cfg, quantum=True, steps_per_epoch=loader.steps_per_epoch)
    _, state_b = init_sc_state(cfg, quantum=True, steps_per_epoch=loader.steps_per_epoch)
    step = make_sc_train_step(model, needs_rng=True)
    rng = jax.random.PRNGKey(123)
    losses_a = []
    for batch in loader.epoch(0):
        rng, sub = jax.random.split(rng)
        state_a, m = step(state_a, batch, sub)
        losses_a.append(float(m["loss"]))

    run = make_sc_scan_steps(model, geom, needs_rng=True)
    scen, user = loader.grid_coords
    rng = jax.random.PRNGKey(123)
    losses_b = []
    for idx, snrs in loader.epoch_chunks(0, k=2):
        subs = []
        for _ in range(idx.shape[0]):
            rng, sub = jax.random.split(rng)
            subs.append(sub)
        state_b, ms = run(
            state_b, jnp.uint32(cfg.data.seed), scen, user, idx, snrs, jnp.stack(subs)
        )
        losses_b.extend(float(v) for v in ms["loss"])

    np.testing.assert_allclose(losses_a, losses_b, rtol=1e-5)
    # Param check is loose for the quantum circuit weights: their gradients are
    # near zero, so Adam's grad/sqrt(v) normalization amplifies float32
    # reassociation differences between the scanned and per-step programs.
    for a, b in zip(jax.tree.leaves(state_a.params), jax.tree.leaves(state_b.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-3, atol=1e-5)


def test_scan_eligible_decision_table():
    """Eligibility gate: K=1 fuses too (the dispatch-gap elimination default);
    single-process dividing mesh yes; non-dividing batch no (with a logged
    warning); scan_steps=0 and train.checkify keep the per-step path. EVERY
    decision emits a structured scan_dispatch record with the reason, so a
    dispatch-bound run is diagnosable from its JSONL alone."""
    from types import SimpleNamespace

    from qdml_tpu.train.scan import scan_eligible

    class Log:
        def __init__(self):
            self.records = []

        def log(self, **kw):
            self.records.append(kw)

        def decision(self):
            recs = [r for r in self.records if r.get("kind") == "scan_dispatch"]
            assert len(recs) == 1 and "reason" in recs[0] and "scan_steps" in recs[0]
            return recs[0]

        def warned(self):
            return any("ignored" in r.get("warning", "") for r in self.records)

    def cfg_with(k, **extra):
        return tiny_cfg(**{"train.scan_steps": k, **extra})

    loader = SimpleNamespace(batch_size=16)
    mesh8 = SimpleNamespace(shape={"data": 8})
    mesh3 = SimpleNamespace(shape={"data": 3})

    # K=1 is a fused scan now: donated carry + in-program synthesis
    log = Log()
    assert scan_eligible(cfg_with(1), None, loader, log)
    assert log.decision()["eligible"] and "fused" in log.decision()["reason"]
    assert scan_eligible(cfg_with(4), None, loader, Log())
    assert scan_eligible(cfg_with(4), mesh8, loader, Log())  # 16 % 8 == 0
    # scan_steps=0 is the explicit opt-out
    log = Log()
    assert not scan_eligible(cfg_with(0), None, loader, log)
    assert "disabled" in log.decision()["reason"]
    # checkify forces per-step dispatch, and says so in the record
    log = Log()
    assert not scan_eligible(cfg_with(1, **{"train.checkify": True}), None, loader, log)
    assert "checkify" in log.decision()["reason"] and log.warned()
    # non-dividing mesh batch: declined with the loader-shape reason
    log = Log()
    assert not scan_eligible(cfg_with(4), mesh3, loader, log)  # 16 % 3 != 0
    assert "loader shape" in log.decision()["reason"] and log.warned()


def test_scan_fused_loop_zero_steady_state_host_transfers(tmp_path):
    """The dispatch-gap contract, pinned off StepClock's counters record: a
    fused train loop's steady-state host-transfer count sits at the probe
    cadence floor — probe_every=1 syncs every steady dispatch, probe_every=0
    pins EXACTLY zero in-dispatch transfers for the whole run."""
    from qdml_tpu.telemetry import run_manifest, set_sink
    from qdml_tpu.telemetry.core import Telemetry
    from qdml_tpu.train.dce import train_dce

    def counters_for(cfg, path):
        tele = Telemetry(str(path), manifest=run_manifest(cfg))
        set_sink(tele)
        try:
            train_dce(cfg)
        finally:
            set_sink(None)
            tele.close()
        import json

        lines = [json.loads(l) for l in open(path) if l.strip()]
        cnt = [l for l in lines if l.get("kind") == "counters"]
        assert cnt, "train loop emitted no counters records"
        return cnt

    # probe_every=0: zero steady-state transfers, every epoch
    cfg = tiny_cfg(**{"train.probe_every": 0})
    for c in counters_for(cfg, tmp_path / "p0.jsonl"):
        assert c["host_transfers"] == 0 and c["host_transfer"] is None
    # probe_every=1: the cadence floor — every steady dispatch transfers
    # (the first dispatch of the run is the compile step, counted separately)
    cfg = tiny_cfg(**{"train.probe_every": 1})
    for c in counters_for(cfg, tmp_path / "p1.jsonl"):
        if c["step"]:
            assert c["host_transfers"] == c["step"]["n"]


def test_scan_program_owns_data_synthesis():
    """The fused K=1 runner takes NO batch argument — synthesis is inside the
    compiled program by construction — and its lowered HLO carries no host
    infeed/outfeed: the data path cannot silently fall back to host feeding."""
    from qdml_tpu.data.channels import ChannelGeometry
    from qdml_tpu.data.datasets import DMLGridLoader
    from qdml_tpu.train.hdce import init_hdce_state, make_hdce_scan_steps

    cfg = tiny_cfg()
    geom = ChannelGeometry.from_config(cfg.data)
    loader = DMLGridLoader(cfg.data, cfg.train.batch_size, "train", geom)
    model, state = init_hdce_state(cfg, loader.steps_per_epoch)
    run = make_hdce_scan_steps(model, geom)
    scen, user = loader.grid_coords
    idx, snrs = next(iter(loader.epoch_chunks(0, k=1)))
    hlo = run.lower(
        state, jnp.uint32(cfg.data.seed), scen, user, idx, snrs
    ).as_text()
    assert "infeed" not in hlo and "outfeed" not in hlo
