// qdml_io — native IO runtime for qdml_tpu.
//
// The reference feeds training from pre-generated .npy files through a torch
// DataLoader with num_workers=0 (Runner_P128_QuantumNAT_onchipQNN.py:24,
// 48-95) — single-threaded host IO feeding 4 GPUs. This library is the
// TPU-framework replacement for that host data path when training from a
// materialised .npy cache:
//
//   * zero-copy .npy access: header parse + mmap (the OS page cache is the
//     shared buffer; no read() copies),
//   * multithreaded row gather: assemble a shuffled batch from row indices
//     into one contiguous pinned-intent buffer, split across worker threads,
//   * an async prefetch pipeline: a slot ring where worker threads fill the
//     next batches while the accelerator consumes the current one, hiding
//     host gather latency behind device step time.
//
// Exposed as a plain C ABI for ctypes (this image has no pybind11); see
// qdml_tpu/runtime/native_io.py for the Python side.
//
// Build: g++ -O3 -std=c++17 -shared -fPIC -pthread qdml_io.cpp -o libqdml_io.so

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

// ---------------------------------------------------------------------------
// .npy file: header parse + mmap
// ---------------------------------------------------------------------------

struct NpyFile {
  int fd = -1;
  void* map = nullptr;
  size_t map_len = 0;
  const uint8_t* data = nullptr;  // first element, inside the mapping
  long shape[8] = {0};
  int ndim = 0;
  int itemsize = 0;
  char typechar = '?';  // 'f' float, 'c' complex, 'i' int, 'u' uint
};

// Parse "{'descr': '<f4', 'fortran_order': False, 'shape': (3, 4), }"
bool parse_header(const std::string& h, NpyFile* f) {
  auto find_val = [&](const char* key) -> std::string {
    size_t k = h.find(key);
    if (k == std::string::npos) return "";
    size_t colon = h.find(':', k);
    if (colon == std::string::npos) return "";
    size_t end = h.find(',', colon);
    // shape tuple contains commas; cut at ')' instead
    size_t open = h.find('(', colon);
    if (open != std::string::npos && open < end) end = h.find(')', open) + 1;
    if (end == std::string::npos) end = h.size();
    return h.substr(colon + 1, end - colon - 1);
  };

  std::string descr = find_val("'descr'");
  size_t q = descr.find('\'');
  if (q == std::string::npos) return false;
  std::string d = descr.substr(q + 1, descr.find('\'', q + 1) - q - 1);
  if (d.size() < 3 || (d[0] != '<' && d[0] != '|' && d[0] != '=')) return false;
  f->typechar = d[1];
  f->itemsize = std::atoi(d.c_str() + 2);
  if (f->itemsize <= 0 || f->itemsize > 64) return false;

  if (find_val("'fortran_order'").find("True") != std::string::npos) return false;

  std::string shape = find_val("'shape'");
  size_t open = shape.find('(');
  size_t close = shape.find(')');
  if (open == std::string::npos || close == std::string::npos) return false;
  std::string tup = shape.substr(open + 1, close - open - 1);
  f->ndim = 0;
  const char* p = tup.c_str();
  while (*p && f->ndim < 8) {
    while (*p == ' ' || *p == ',') ++p;
    if (!*p) break;
    char* endp = nullptr;
    long v = std::strtol(p, &endp, 10);
    if (endp == p) break;
    f->shape[f->ndim++] = v;
    p = endp;
  }
  if (f->ndim == 0) {  // 0-d scalar: treat as shape (1,)
    f->shape[0] = 1;
    f->ndim = 1;
  }
  return true;
}

}  // namespace

extern "C" {

void* qdml_npy_open(const char* path) {
  auto* f = new NpyFile();
  f->fd = ::open(path, O_RDONLY);
  if (f->fd < 0) {
    delete f;
    return nullptr;
  }
  struct stat st;
  if (fstat(f->fd, &st) != 0 || st.st_size < 12) {
    ::close(f->fd);
    delete f;
    return nullptr;
  }
  f->map_len = static_cast<size_t>(st.st_size);
  f->map = mmap(nullptr, f->map_len, PROT_READ, MAP_PRIVATE, f->fd, 0);
  if (f->map == MAP_FAILED) {
    ::close(f->fd);
    delete f;
    return nullptr;
  }
  const uint8_t* b = static_cast<const uint8_t*>(f->map);
  if (std::memcmp(b, "\x93NUMPY", 6) != 0) goto fail;
  {
    int major = b[6];
    size_t hlen, hoff;
    if (major == 1) {
      hlen = b[8] | (b[9] << 8);
      hoff = 10;
    } else {  // v2/v3: 4-byte header length
      hlen = static_cast<size_t>(b[8]) | (static_cast<size_t>(b[9]) << 8) |
             (static_cast<size_t>(b[10]) << 16) | (static_cast<size_t>(b[11]) << 24);
      hoff = 12;
    }
    if (hoff + hlen > f->map_len) goto fail;
    std::string header(reinterpret_cast<const char*>(b + hoff), hlen);
    if (!parse_header(header, f)) goto fail;
    f->data = b + hoff + hlen;
    long total = 1;
    for (int i = 0; i < f->ndim; ++i) total *= f->shape[i];
    if (f->data + static_cast<size_t>(total) * f->itemsize >
        b + f->map_len) goto fail;
  }
  return f;
fail:
  munmap(f->map, f->map_len);
  ::close(f->fd);
  delete f;
  return nullptr;
}

int qdml_npy_info(void* h, long* shape_out, int* ndim, int* itemsize, char* typechar) {
  if (!h) return -1;
  auto* f = static_cast<NpyFile*>(h);
  for (int i = 0; i < f->ndim; ++i) shape_out[i] = f->shape[i];
  *ndim = f->ndim;
  *itemsize = f->itemsize;
  *typechar = f->typechar;
  return 0;
}

const void* qdml_npy_data(void* h) {
  return h ? static_cast<NpyFile*>(h)->data : nullptr;
}

void qdml_npy_close(void* h) {
  if (!h) return;
  auto* f = static_cast<NpyFile*>(h);
  munmap(f->map, f->map_len);
  ::close(f->fd);
  delete f;
}

// ---------------------------------------------------------------------------
// Threaded row gather
// ---------------------------------------------------------------------------

void qdml_gather_rows(const void* src, long row_bytes, const long* idx, long n,
                      void* dst, int n_threads) {
  const uint8_t* s = static_cast<const uint8_t*>(src);
  uint8_t* d = static_cast<uint8_t*>(dst);
  if (n_threads <= 1 || n < 64) {
    for (long i = 0; i < n; ++i)
      std::memcpy(d + i * row_bytes, s + idx[i] * row_bytes, row_bytes);
    return;
  }
  std::vector<std::thread> ts;
  long chunk = (n + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    long lo = t * chunk, hi = std::min(n, lo + chunk);
    if (lo >= hi) break;
    ts.emplace_back([=]() {
      for (long i = lo; i < hi; ++i)
        std::memcpy(d + i * row_bytes, s + idx[i] * row_bytes, row_bytes);
    });
  }
  for (auto& t : ts) t.join();
}

// ---------------------------------------------------------------------------
// Async prefetch pipeline: slot ring filled by a worker pool
// ---------------------------------------------------------------------------

namespace {

struct Job {
  int slot;
  std::vector<long> idx;
};

struct Prefetcher {
  const uint8_t* src;
  long row_bytes;
  long batch;
  int n_slots;
  std::vector<std::vector<uint8_t>> buffers;
  std::vector<std::atomic<int>> state;  // 0 free, 1 filling, 2 ready

  std::deque<Job> queue;
  std::mutex mu;
  std::condition_variable cv_job;
  std::condition_variable cv_done;
  std::vector<std::thread> workers;
  bool stop = false;

  Prefetcher(const void* s, long rb, int slots, long b, int n_threads)
      : src(static_cast<const uint8_t*>(s)),
        row_bytes(rb),
        batch(b),
        n_slots(slots),
        buffers(slots),
        state(slots) {
    for (int i = 0; i < slots; ++i) {
      buffers[i].resize(static_cast<size_t>(rb) * b);
      state[i].store(0);
    }
    for (int t = 0; t < n_threads; ++t)
      workers.emplace_back([this]() { this->run(); });
  }

  void run() {
    for (;;) {
      Job job;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_job.wait(lk, [&] { return stop || !queue.empty(); });
        if (stop && queue.empty()) return;
        job = std::move(queue.front());
        queue.pop_front();
      }
      uint8_t* d = buffers[job.slot].data();
      for (size_t i = 0; i < job.idx.size(); ++i)
        std::memcpy(d + i * row_bytes, src + job.idx[i] * row_bytes, row_bytes);
      {
        // Publish under the lock: a waiter that just evaluated the predicate
        // false must not miss the notify (lost-wakeup race).
        std::lock_guard<std::mutex> lk(mu);
        state[job.slot].store(2);
      }
      cv_done.notify_all();
    }
  }

  ~Prefetcher() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stop = true;
    }
    cv_job.notify_all();
    for (auto& w : workers) w.join();
  }
};

}  // namespace

void* qdml_prefetch_create(const void* src, long row_bytes, int n_slots,
                           long batch, int n_threads) {
  if (!src || row_bytes <= 0 || n_slots <= 0 || batch <= 0) return nullptr;
  return new Prefetcher(src, row_bytes, n_slots, batch,
                        n_threads > 0 ? n_threads : 2);
}

// Submit a fill of `n` (<= batch) rows; returns the slot id, or -1 if no slot
// is free (caller must release slots after consuming them).
int qdml_prefetch_submit(void* p, const long* idx, long n) {
  auto* pf = static_cast<Prefetcher*>(p);
  if (!pf || n > pf->batch) return -1;
  int slot = -1;
  for (int i = 0; i < pf->n_slots; ++i) {
    int expected = 0;
    if (pf->state[i].compare_exchange_strong(expected, 1)) {
      slot = i;
      break;
    }
  }
  if (slot < 0) return -1;
  {
    std::lock_guard<std::mutex> lk(pf->mu);
    pf->queue.push_back(Job{slot, std::vector<long>(idx, idx + n)});
  }
  pf->cv_job.notify_one();
  return slot;
}

int qdml_prefetch_wait(void* p, int slot) {
  auto* pf = static_cast<Prefetcher*>(p);
  if (!pf || slot < 0 || slot >= pf->n_slots) return -1;
  std::unique_lock<std::mutex> lk(pf->mu);
  pf->cv_done.wait(lk, [&] { return pf->state[slot].load() == 2; });
  return 0;
}

const void* qdml_prefetch_buffer(void* p, int slot) {
  auto* pf = static_cast<Prefetcher*>(p);
  if (!pf || slot < 0 || slot >= pf->n_slots) return nullptr;
  return pf->buffers[slot].data();
}

void qdml_prefetch_release(void* p, int slot) {
  auto* pf = static_cast<Prefetcher*>(p);
  if (pf && slot >= 0 && slot < pf->n_slots) pf->state[slot].store(0);
}

void qdml_prefetch_destroy(void* p) { delete static_cast<Prefetcher*>(p); }

}  // extern "C"
