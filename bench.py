#!/usr/bin/env python
"""Benchmark harness. Prints ONE JSON line:

    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "platform": ...,
     "mfu": ..., "details": {...}}

With ``--out=PATH`` (or ``QDML_BENCH_TELEMETRY_OUT``) the same record is also
written as a telemetry JSONL — a run-manifest header line (device topology,
git SHA, knob provenance from the measuring child) followed by the record —
the artifact shape ``qdml_tpu.cli report`` consumes and regression-gates
against a committed baseline (docs/TELEMETRY.md). Per-measurement details
carry ``compile_s`` and ``dispatch_ms`` p50/p95/max alongside the mean rate,
plus a ``cost`` block (XLA FLOPs/bytes/roofline from the step's lowering —
``telemetry/cost.py``, docs/FLIGHTREC.md) so the report can tell a slowdown
from a changed program.

Headline metric: full fused HDCE training-step throughput over the 3x3
scenario/user DML grid at the reference batch size (256/cell => 2304
samples/step; the reference's nine-sequential-backwards loop,
``Runner_P128_QuantumNAT_onchipQNN.py:181-204``). On TPU the headline is the
scan-fused bfloat16 path (``train.scan_steps=16``: 16 train steps per device
dispatch with each step's batch synthesized ON DEVICE inside the scan — the
throughput a real training run achieves end to end, data generation
included); on the CPU fallback it is the reference-dtype float32
step-per-dispatch measurement — the ``dtype`` and ``unit`` fields record
which. ``details`` always carries the per-dispatch HDCE step in both dtypes
plus the quantum-classifier (QSC) step on the dense and Pallas circuit
backends, each with achieved model FLOP/s and MFU against the chip's bf16
peak (MFU counts model FLOPs only — the in-scan data synthesis is unpaid
overhead, which makes the scan MFU an honest end-to-end figure).

Robustness (VERDICT round 1, weak #1): the parent process never imports jax.
It probes the TPU backend in a subprocess with a hard timeout and retries
with EXPONENTIAL backoff under a total probe budget
(``QDML_BENCH_PROBE_BUDGET_S``; the tunnelled axon backend has been observed
both to fail fast and to hang at interpreter start, and BENCH_r05 showed an
unbudgeted schedule degenerating into a ~1000s storm of identical timeout
tails); every measurement runs in a child with its own timeout. If the TPU
is unreachable the harness still emits a finite number measured on CPU
(``platform: "cpu_fallback"``) plus the TPU error — a structured record
instead of a bare traceback — with ``probe_attempts`` summarizing the probe
campaign (attempt count, window, per-outcome counts) and a single structured
``probe_unavailable`` outcome when no probe ever succeeded, so a
down-all-window tunnel is provable from the artifact alone without N copies
of the same tail.

``vs_baseline`` is the speedup over a faithful torch-CPU implementation of
the reference training step, measured against a FIXED committed constant
(:data:`REFERENCE_TORCH_CPU_SPS`) so the number means the same thing in
every round's artifact regardless of which host runs the harness (VERDICT
r2 weak #6: the live measurement swings 5x between the driver host and the
TPU VM). The live same-host measurement is still recorded as
``torch_cpu_reference_sps_live`` for context, and ``vs_baseline_live``
divides by it: on the 1-core driver host every CPU measurement scales with
whatever else the host runs, so the live ratio — both sides measured in the
same window — is the contention-robust figure for cpu_fallback records. The reference publishes no
hardware throughput; BASELINE.md's >= 3x-single-V100 target remains
unmeasurable without a V100 — the committed CPU constant is the anchor.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

# bf16 peak FLOP/s by TPU generation (PALLAS_AXON_TPU_GEN; default v5e).
_PEAK_BF16 = {"v4": 275e12, "v5e": 197e12, "v5p": 459e12, "v6e": 918e12}

# Fixed cross-round baseline: the reference-equivalent torch-CPU training
# step (measure_torch_cpu_reference below) as measured on the round-2 driver
# host and recorded in the committed BENCH_r02.json
# ("torch_cpu_reference_sps": 1389.3). Every round's ``vs_baseline`` divides
# by THIS constant, so the headline is comparable across rounds and hosts.
# NOTE the older self-reported results/bench_tpu_v5e_r2.json predates the
# constant and divided by its own host's much slower live baseline (278.5
# sps on the TPU VM -> "2928x"); against this constant the same measurement
# is 587x. Records since round 3 carry both the constant and the live
# number so the two scales can never be conflated again.
REFERENCE_TORCH_CPU_SPS = 1389.3

_GRID = (3, 3)
_CELL_BS = 256


# ---------------------------------------------------------------------------
# FLOP model (per sample, forward; train step ~= 3x forward)
# ---------------------------------------------------------------------------


def hdce_fwd_flops_per_sample(cfg) -> float:
    """Conv trunk + estimation head, derived from the same config the bench
    instantiates (so a changed default can't silently desynchronize MFU)."""
    h, w = cfg.image_hw
    f = cfg.model.features
    k2 = cfg.model.kernel_size**2
    conv = 2 * h * w * k2 * 2 * f  # first block: 2 (re/im) input channels
    conv += (cfg.model.n_conv_layers - 1) * (2 * h * w * k2 * f * f)
    head = 2 * cfg.feat_dim * cfg.h_out_dim
    return float(conv + head)


def qsc_fwd_flops_per_sample(cfg) -> float:
    """CNN preprocess + dense-unitary circuit (2^n x 2^n complex matmul)."""
    h, w = cfg.image_hw
    n_q = cfg.quantum.n_qubits
    # preprocess: Conv 2->16 on (h, w), Conv 16->32 on (h/2, w/2), Dense -> n_q
    flat = 32 * (h // 4) * (w // 4)
    pre = 2 * h * w * 9 * 2 * 16 + 2 * (h // 2) * (w // 2) * 9 * 16 * 32
    pre += 2 * flat * n_q
    dim = 1 << n_q
    # real product-state amp through U^T (two real matvecs) + |.|^2 sign
    # contraction — the closed-form dense/pallas formulation
    circ = 4.0 * dim * dim + 2.0 * dim * n_q
    head = 2 * n_q * cfg.quantum.n_classes
    return float(pre + circ + head)


# ---------------------------------------------------------------------------
# Child: actual measurements (runs under either backend)
# ---------------------------------------------------------------------------


def _timed_sps(step, state, batch, sync, max_steps: int, budget_s: float) -> dict:
    """Timing record for an async-dispatched jitted step:
    ``{"sps", "compile_s", "dispatch_ms", "host_transfers"}``.

    Sizes the measured run from one SYNCED step so the budget bounds device
    time, not just dispatch time (async dispatch enqueues at Python speed —
    an un-synced while loop would queue all max_steps regardless of real step
    cost and blow the child's wall-clock timeout on a slow backend).

    ``compile_s`` is the warmup (compile + first executions) wall time;
    ``dispatch_ms`` are p50/p95/max of the per-iteration enqueue intervals of
    the timed loop — device-backpressured after the pipeline fills, so the
    tail percentiles surface stalls the mean rate hides. The headline sps
    math (n / synced wall) is unchanged.

    ``host_transfers`` counts device->host syncs issued INSIDE the timed
    steady-state loop. The loop is transfer-free by construction (the one
    drain sync sits after it), and the loop body runs under jax's
    device-to-host transfer guard at the STRICT level
    (``disallow_explicit`` — plain ``disallow`` waves explicit
    ``jax.device_get`` through, the codebase's standard fetch idiom), so on
    an accelerator backend a reintroduced steady-state fetch raises instead
    of silently re-serializing the pipeline; ``run_child`` converts that
    trip into a ``host_transfers: 1`` error entry the report's gate fails
    on. Caveat, verified on this jax: the guard is INERT on the CPU backend
    (same-memory "transfers" are not intercepted), so cpu_fallback records'
    0 is structural (no fetch in the loop source), not guard-enforced — the
    dispatch gap being gated is an accelerator property anyway. The
    committed artifact's 0 arms the reappearing-transfer gate in
    ``qdml-tpu report``."""
    import jax

    from qdml_tpu.telemetry import Histogram

    t_c0 = time.perf_counter()
    for _ in range(2):  # warmup + compile
        state, m = step(state, batch)
    sync(m)
    compile_s = time.perf_counter() - t_c0
    t0 = time.perf_counter()
    state, m = step(state, batch)
    sync(m)
    est = max(time.perf_counter() - t0, 1e-4)
    n = max(3, min(max_steps, int(budget_s / est)))
    hist = Histogram()
    t0 = time.perf_counter()
    with jax.transfer_guard_device_to_host("disallow_explicit"):
        for _ in range(n):
            t1 = time.perf_counter()
            state, m = step(state, batch)
            hist.add(time.perf_counter() - t1)
    sync(m)  # one deliberate end-of-window drain, outside the steady state
    return {
        "sps": n / (time.perf_counter() - t0),
        "compile_s": round(compile_s, 3),
        "dispatch_ms": hist.summary(),
        # 0 because the loop completed: on accelerator backends the strict
        # guard raises on ANY in-window sync (explicit included) and
        # run_child records the trip as host_transfers=1, so this value is
        # load-bearing, not decorative; on CPU it is structural (see
        # docstring caveat)
        "host_transfers": 0,
    }


def _grid_coords():
    """(scen, user, idx) coordinate grids for one (S, U, B) bench batch."""
    import jax.numpy as jnp

    s, u = _GRID
    scen = jnp.broadcast_to(jnp.arange(s)[:, None, None], (s, u, _CELL_BS))
    user = jnp.broadcast_to(jnp.arange(u)[None, :, None], (s, u, _CELL_BS))
    idx = jnp.broadcast_to(jnp.arange(_CELL_BS)[None, None, :], (s, u, _CELL_BS))
    return scen, user, idx


def _make_grid_batch(cfg):
    import jax.numpy as jnp

    from qdml_tpu.data.channels import ChannelGeometry
    from qdml_tpu.data.datasets import make_network_batch

    geom = ChannelGeometry.from_config(cfg.data)
    scen, user, idx = _grid_coords()
    return make_network_batch(
        jnp.uint32(0), scen, user, idx, jnp.float32(cfg.data.snr_db), geom
    )


def _bench_hdce(
    dtype: str,
    max_steps: int,
    budget_s: float,
    features: int = 32,
    conv_impl: str = "auto",
) -> dict:
    """``features`` widens the conv trunk beyond the reference's 32 channels
    — the round-4 lane-occupancy scaling probe (scripts/r4_perf_session.py);
    the FLOP model derives from the same cfg so MFU stays consistent.
    ``conv_impl`` overrides the platform-resolved conv lowering
    (scripts/r4_cpu_fallback_profile.py measures both on CPU)."""
    from qdml_tpu.config import DataConfig, ExperimentConfig, ModelConfig, TrainConfig
    from qdml_tpu.train.hdce import init_hdce_state, make_hdce_train_step

    cfg = ExperimentConfig(
        data=DataConfig(),
        model=ModelConfig(dtype=dtype, features=features, conv_impl=conv_impl),
        train=TrainConfig(batch_size=_CELL_BS, n_epochs=1),
    )
    from qdml_tpu.models.cnn import resolve_conv_impl

    batch = _make_grid_batch(cfg)
    batch = {k: batch[k] for k in ("yp_img", "h_label", "h_perf")}
    model, state = init_hdce_state(cfg, steps_per_epoch=100)
    # probes=False: the timed program must match the committed baselines'
    # step (and keep model_tflops honest) — probe overhead is a training-
    # run concern, toggled there by train.probe_every
    step = make_hdce_train_step(model, state.tx, probes=False)
    from qdml_tpu.telemetry import cost as _cost

    # XLA cost accounting off the step's LOWERING (traces, never compiles —
    # the timed warmup below still performs the one real compile)
    cost_rec = _cost.analyze_jit(step, state, batch)
    t = _timed_sps(
        step, state, batch, lambda m: float(m["loss"]), max_steps, budget_s
    )
    samples = t["sps"] * _GRID[0] * _GRID[1] * _CELL_BS
    tflops = samples * 3.0 * hdce_fwd_flops_per_sample(cfg) / 1e12
    return {
        "samples_per_sec": round(samples, 1),
        "model_tflops": round(tflops, 3),
        "compile_s": t["compile_s"],
        "dispatch_ms": t["dispatch_ms"],
        "host_transfers": t["host_transfers"],
        "cost": cost_rec,
        # achieved-vs-roofline fraction: XLA's own program accounting placed
        # on the roofline by THIS measurement's rate (telemetry/cost.py,
        # docs/ROOFLINE.md — the report gates a drop on the fused path)
        "roofline": _cost.achieved_roofline(cost_rec, t["sps"]),
        # the lowering this measurement actually ran (proves "auto" engaged
        # shift_matmul in the fallback path — VERDICT r4 weak #1 asked
        # whether 206-vs-451 sps meant the fix wasn't engaging; it was)
        "conv_impl": resolve_conv_impl(conv_impl),
    }


def _bench_hdce_scan(
    dtype: str,
    k: int,
    max_steps: int,
    budget_s: float,
    rng_impl: str = "threefry",
    trig_impl: str = "direct",
    moments_dtype: str = "float32",
) -> dict:
    """The scan-fused training path (qdml_tpu.train.hdce.make_hdce_scan_steps):
    K steps per device dispatch, batches synthesized on-device inside the
    scan. This is the throughput a real training run achieves with
    ``train.scan_steps=K`` — it removes the per-step host dispatch gap that
    caps the K=1 wall MFU at ~0.27 on the tunnelled backend
    (docs/ROOFLINE.md: 1.42 ms device-busy vs 2.9 ms wall). ``rng_impl``
    selects the generator PRNG (DataConfig.rng_impl): in-scan synthesis pays
    for its random bits on device, so the hardware-RBG stream is a real
    training-throughput lever."""
    import jax.numpy as jnp

    from qdml_tpu.config import DataConfig, ExperimentConfig, ModelConfig, TrainConfig
    from qdml_tpu.data.channels import ChannelGeometry
    from qdml_tpu.train.hdce import init_hdce_state, make_hdce_scan_steps

    cfg = ExperimentConfig(
        data=DataConfig(rng_impl=rng_impl, trig_impl=trig_impl),
        model=ModelConfig(dtype=dtype),
        train=TrainConfig(
            batch_size=_CELL_BS, n_epochs=1, moments_dtype=moments_dtype
        ),
    )
    geom = ChannelGeometry.from_config(cfg.data)
    s, u = _GRID
    scen, user, idx1 = _grid_coords()
    idx = jnp.broadcast_to(idx1[None], (k, s, u, _CELL_BS)).astype(jnp.int32)
    snrs = jnp.full((k,), float(cfg.data.snr_db), jnp.float32)
    model, state = init_hdce_state(cfg, steps_per_epoch=100)
    run = make_hdce_scan_steps(model, geom, probes=False)  # baseline-comparable program
    seed = jnp.uint32(0)
    from qdml_tpu.telemetry import cost as _cost

    cost_rec = _cost.analyze_jit(run, state, seed, scen, user, idx, snrs)

    def step(state, _):
        return run(state, seed, scen, user, idx, snrs)

    t = _timed_sps(
        step, state, None, lambda m: float(m["loss"][-1]), max_steps, budget_s
    )
    samples = t["sps"] * k * s * u * _CELL_BS
    tflops = samples * 3.0 * hdce_fwd_flops_per_sample(cfg) / 1e12
    out = {
        "samples_per_sec": round(samples, 1),
        "model_tflops": round(tflops, 3),
        "compile_s": t["compile_s"],
        "dispatch_ms": t["dispatch_ms"],
        "host_transfers": t["host_transfers"],
        "scan_steps": k,
        "cost": cost_rec,
        "roofline": _cost.achieved_roofline(cost_rec, t["sps"]),
    }
    if rng_impl != "threefry":
        out["rng_impl"] = rng_impl
    if trig_impl != "direct":
        out["trig_impl"] = trig_impl
    if moments_dtype != "float32":
        out["moments_dtype"] = moments_dtype
    return out


def _bench_qsc(
    backend: str,
    max_steps: int,
    budget_s: float,
    n_qubits: int = 6,
    tune: bool = False,
) -> dict:
    """One QSC train-step measurement on a FIXED circuit impl (``backend``)
    or, with ``tune=True`` and ``backend="auto"``, on the autotuned
    dispatcher path — the tuner runs first (its compiles land outside the
    timed loop) and the record carries the chosen impl plus every
    candidate's micro-bench timings, so the artifact can say what the
    winner beat. Every record names the impl that actually ran
    (``quantum_impl``)."""
    import jax

    from qdml_tpu.config import (
        DataConfig,
        ExperimentConfig,
        QuantumConfig,
        TrainConfig,
    )
    from qdml_tpu.train.qsc import init_sc_state, make_sc_train_step

    cfg = ExperimentConfig(
        data=DataConfig(),
        # fixed-impl benches must never consult (or write) the table; the
        # auto bench always tunes, on every platform — the candidates ARE
        # the artifact
        quantum=QuantumConfig(
            backend=backend, n_qubits=n_qubits, autotune="on" if tune else "off"
        ),
        train=TrainConfig(batch_size=_CELL_BS, n_epochs=1),
    )
    from qdml_tpu.quantum import autotune as _at
    from qdml_tpu.quantum.circuits import resolve_impl

    circuit_batch = _GRID[0] * _GRID[1] * _CELL_BS
    # force=True: the artifact's candidate timings must come from THIS
    # bench window, never a previous session's persisted entry
    at_entry = _at.prewarm(cfg, batch=circuit_batch, force=True) if tune else None
    batch = _make_grid_batch(cfg)
    batch = {k: batch[k] for k in ("yp_img", "indicator")}
    model, state = init_sc_state(cfg, quantum=True, steps_per_epoch=100)
    step = make_sc_train_step(model, needs_rng=False, probes=False)  # baseline-comparable
    rng = jax.random.PRNGKey(0)
    from qdml_tpu.telemetry import cost as _cost

    cost_rec = _cost.analyze_jit(step, state, batch, rng)

    def step2(state, b):
        return step(state, b, rng)

    t = _timed_sps(
        step2, state, batch, lambda m: float(m["loss"]), max_steps, budget_s
    )
    samples = t["sps"] * _GRID[0] * _GRID[1] * _CELL_BS
    tflops = samples * 3.0 * qsc_fwd_flops_per_sample(cfg) / 1e12
    out = {
        "samples_per_sec": round(samples, 1),
        "model_tflops": round(tflops, 3),
        "compile_s": t["compile_s"],
        "dispatch_ms": t["dispatch_ms"],
        "host_transfers": t["host_transfers"],
        "cost": cost_rec,
        "roofline": _cost.achieved_roofline(cost_rec, t["sps"]),
        # the circuit implementation this measurement actually dispatched
        "quantum_impl": resolve_impl(
            cfg.quantum.impl,
            cfg.quantum.backend,
            n_qubits,
            cfg.quantum.n_layers,
            circuit_batch,
            mode="train",
        ),
    }
    if at_entry is not None:
        out["autotune"] = {
            "key": at_entry["key"],
            "best_train": at_entry["best_train"],
            "best_fwd": at_entry["best_fwd"],
            "candidates": at_entry["candidates"],
        }
    return out


def _bench_qsc_scan(
    backend: str,
    k: int,
    max_steps: int,
    budget_s: float,
    n_qubits: int = 6,
    tune: bool = False,
) -> dict:
    """Scan-fused quantum-classifier training (make_sc_scan_steps): K steps
    per dispatch with on-device batch synthesis — the same dispatch-gap
    removal the HDCE headline uses, applied to the QSC path whose K=1 step
    is ~entirely host gap (<1% MFU, docs/ROOFLINE.md). At K=1 this measures
    THE default ``train-qsc`` hot path since scan fusion took over
    step-per-dispatch training (``train/scan.py``): one ``lax.scan`` body per
    dispatch, donated carry, batch synthesized in-program, zero steady-state
    host transfers.

    Measured with the FAST generator levers (rng_impl='rbg',
    trig_impl='split'), NOT a default-config `train-qsc` run (ADVICE r5 low:
    the old docstring claimed "real run" throughput while hardcoding the
    levers); both knobs are recorded in the returned dict — and in the
    run-manifest header of any bench telemetry JSONL — so the record can
    never read as a default-stream measurement. ``tune=True`` (with
    ``backend="auto"``) runs the autotuner first, exactly like
    :func:`_bench_qsc`: the record then carries the dispatched winner and
    every candidate's timings."""
    import jax.numpy as jnp

    from qdml_tpu.config import (
        DataConfig,
        ExperimentConfig,
        QuantumConfig,
        TrainConfig,
    )
    from qdml_tpu.data.channels import ChannelGeometry
    from qdml_tpu.train.qsc import init_sc_state, make_sc_scan_steps

    cfg = ExperimentConfig(
        data=DataConfig(rng_impl="rbg", trig_impl="split"),
        quantum=QuantumConfig(
            backend=backend, n_qubits=n_qubits, autotune="on" if tune else "off"
        ),
        train=TrainConfig(batch_size=_CELL_BS, n_epochs=1),
    )
    from qdml_tpu.quantum import autotune as _at
    from qdml_tpu.quantum.circuits import resolve_impl

    circuit_batch = _GRID[0] * _GRID[1] * _CELL_BS
    at_entry = _at.prewarm(cfg, batch=circuit_batch, force=True) if tune else None
    geom = ChannelGeometry.from_config(cfg.data)
    s, u = _GRID
    scen, user, idx1 = _grid_coords()
    idx = jnp.broadcast_to(idx1[None], (k, s, u, _CELL_BS)).astype(jnp.int32)
    snrs = jnp.full((k,), float(cfg.data.snr_db), jnp.float32)
    model, state = init_sc_state(cfg, quantum=True, steps_per_epoch=100)
    run = make_sc_scan_steps(model, geom, needs_rng=False, probes=False)  # baseline-comparable
    seed = jnp.uint32(0)
    # the scan machinery always threads a (K, 2) key stack (QuantumNAT noise
    # stream); with needs_rng=False the keys are carried but unused
    import jax as _jax

    from qdml_tpu.train.scan import presplit_keys

    _, rngs = presplit_keys(_jax.random.PRNGKey(0), k)
    from qdml_tpu.telemetry import cost as _cost

    cost_rec = _cost.analyze_jit(run, state, seed, scen, user, idx, snrs, rngs)

    def step(state, _):
        return run(state, seed, scen, user, idx, snrs, rngs)

    t = _timed_sps(
        step, state, None, lambda m: float(m["loss"][-1]), max_steps, budget_s
    )
    samples = t["sps"] * k * s * u * _CELL_BS
    tflops = samples * 3.0 * qsc_fwd_flops_per_sample(cfg) / 1e12
    out = {
        "samples_per_sec": round(samples, 1),
        "model_tflops": round(tflops, 3),
        "compile_s": t["compile_s"],
        "dispatch_ms": t["dispatch_ms"],
        "host_transfers": t["host_transfers"],
        "scan_steps": k,
        "backend": backend,
        "cost": cost_rec,
        "roofline": _cost.achieved_roofline(cost_rec, t["sps"]),
        # the circuit implementation this measurement actually dispatched
        "quantum_impl": resolve_impl(
            cfg.quantum.impl,
            cfg.quantum.backend,
            n_qubits,
            cfg.quantum.n_layers,
            circuit_batch,
            mode="train",
        ),
        # the non-default generator levers this measurement ran with
        "rng_impl": cfg.data.rng_impl,
        "trig_impl": cfg.data.trig_impl,
    }
    if at_entry is not None:
        out["autotune"] = {
            "key": at_entry["key"],
            "best_train": at_entry["best_train"],
            "best_fwd": at_entry["best_fwd"],
            "candidates": at_entry["candidates"],
        }
    return out


def _bench_qsc_scaling(
    budget_s: float,
    n_values=None,
    n_layers: int = 3,
    mps_chi: int = 16,
    table_path: str | None = None,
) -> dict:
    """The qubit-scaling axis (``qsc_scaling``): one measured point per n in
    the 4..24 grid — the autotuner races every impl eligible at that (n,
    topology), the DISPATCHER's winner is timed as a train step (one jitted
    ``value_and_grad`` over the circuit, the shape train loops dispatch), and
    the point records steps/s, samples/s, XLA cost (flops / bytes / peak
    temp memory), achieved roofline, the chosen ``quantum_impl``, the
    ``mps_chi`` raced, and every candidate's micro-bench timings — so
    BENCH_r06 can plot the impl crossover points straight off the artifact.

    Candidate policy (every exclusion is RECORDED per point — a silent cap
    would read as "covered everything"): the per-topology
    ``autotune.eligible_impls`` set, minus the pallas kernels off-TPU (they
    only run in interpret mode there: a pure-python emulation whose timings
    say nothing about dispatch), minus ``sharded_statevector`` past n=16 on
    the CPU harness (compiling grad-of-250-collectives programs over 8
    virtual devices costs minutes per point; on real ICI hardware the
    window stays open). Per-n batches shrink with the statevector footprint
    (:func:`qdml_tpu.eval.sweep.scaling_batch`) — each n gates only against
    itself, so cross-n batches need not match."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from qdml_tpu.eval.sweep import QUBIT_SCALING_GRID, scaling_batch, scaling_chi
    from qdml_tpu.quantum import autotune as _at
    from qdml_tpu.quantum.circuits import run_circuit
    from qdml_tpu.telemetry import cost as _cost

    platform = jax.default_backend()
    devs = _at.model_axis_devices()
    if table_path:
        _at.set_table_path(table_path)
    points = []
    for n in n_values or QUBIT_SCALING_GRID:
        batch = scaling_batch(n)
        chi = scaling_chi(n, mps_chi)
        impls = _at.eligible_impls(n, platform, devs)
        excluded = []
        if platform != "tpu":
            excluded += [
                {"impl": i, "reason": "pallas off-TPU runs in interpret mode"}
                for i in impls
                if i.startswith("pallas")
            ]
            if n > 16 and "sharded_statevector" in impls:
                excluded.append(
                    {
                        "impl": "sharded_statevector",
                        "reason": (
                            "cpu-harness compile budget (grad of a ~250-"
                            "collective program over virtual devices); "
                            "window open on real ICI hardware"
                        ),
                    }
                )
        drop = {e["impl"] for e in excluded}
        impls = [i for i in impls if i not in drop]
        point: dict = {
            "n_qubits": n,
            "dim": 1 << n,
            "batch": batch,
            "candidates_raced": impls,
        }
        if excluded:
            point["excluded"] = excluded
        try:
            entry = _at.ensure(
                n,
                n_layers,
                batch,
                path=table_path,
                force=True,
                impls=impls,
                budget_s=budget_s,
                mps_chi=chi,
            )
            winner = entry.get("best_train")
            point["candidates"] = entry["candidates"]
            if winner is None:
                point["error"] = "no candidate ran (see candidates.*.error)"
                points.append(point)
                continue
            point["quantum_impl"] = winner
            # chi belongs to the mps run, not the point: attribute it to the
            # winner only when mps won, and to the raced mps candidate
            # otherwise — a tensor winner's row must not claim a bond dim
            if winner == "mps":
                point["mps_chi"] = chi
            elif isinstance(entry["candidates"].get("mps"), dict):
                entry["candidates"]["mps"].setdefault("mps_chi", chi)
            # The winner's train step, timed and costed at this exact shape:
            # the measured number IS best-of-impls (the dispatcher already
            # raced the rest — their timings sit next to it in candidates).
            rng = np.random.default_rng(0)
            angles = jnp.asarray(
                rng.uniform(-1, 1, (batch, n)).astype(np.float32)
            )
            weights = jnp.asarray(
                rng.uniform(0, 2 * np.pi, (n_layers, n, 2)).astype(np.float32)
            )
            step = jax.jit(
                jax.value_and_grad(
                    lambda w, a: jnp.sum(
                        run_circuit(
                            a, w, n, n_layers, backend=winner, mps_chi=chi
                        )
                        ** 2
                    )
                )
            )
            cost_rec = _cost.analyze_jit(step, weights, angles)
            # autotune's own median-of-reps timer: the point's number is
            # measured the same way the candidates it beat were
            ms = _at._time_callable(step, (weights, angles), budget_s, 30)
            sps = 1e3 / ms
            point["train_ms"] = round(ms, 4)
            point["steps_per_sec"] = round(sps, 3)
            point["samples_per_sec"] = round(sps * batch, 1)
            point["cost"] = cost_rec
            point["peak_temp_bytes"] = cost_rec.get("peak_temp_bytes")
            point["roofline"] = _cost.achieved_roofline(cost_rec, sps)
        except Exception as e:  # lint: disable=broad-except(point isolation: one n failing must not kill the sweep's other points; the error is recorded on the point)
            point["error"] = f"{type(e).__name__}: {e}"
        points.append(point)
    return {
        "points": points,
        "n_layers": n_layers,
        "devices_on_model": devs,
        "platform": platform,
        "mps_chi": mps_chi,
        "table": _at.table_path(table_path),
    }


def run_scaling_child(out_path: str | None = None) -> int:
    """The qubit-scaling sweep as its own child: compiles at n=20+ cost
    minutes each on the CPU harness, so the sweep never rides the default
    bench child's budget — ``bench.py --scaling`` (or
    ``scripts/qubit_scaling_sweep.py``, which also forces the 8-virtual-
    device topology) runs it deliberately. Prints one JSON record; with
    ``out_path`` also writes the manifest-headed telemetry JSONL."""
    import jax

    from qdml_tpu.eval.sweep import impl_agreement, scaling_chi
    from qdml_tpu.telemetry import run_manifest

    budget = float(os.environ.get("QDML_SCALING_BUDGET_S", "2.0"))
    table = os.environ.get("QDML_SCALING_TABLE") or None
    grid = os.environ.get("QDML_SCALING_GRID")  # "4,14" (tests/smoke); default full
    n_values = tuple(int(x) for x in grid.split(",")) if grid else None
    scaling = _bench_qsc_scaling(budget, n_values=n_values, table_path=table)
    # numerics cross-check per point (eval half of the axis): winner vs an
    # independent formulation — dense/tensor where they exist, mps-vs-
    # sharded past them (truncation error IS the number at small chi)
    for p in scaling["points"]:
        impl = p.get("quantum_impl")
        if impl is None:
            continue
        try:
            p["agreement"] = impl_agreement(
                p["n_qubits"],
                impl,
                n_layers=scaling["n_layers"],
                batch=min(4, p["batch"]),
                mps_chi=scaling_chi(p["n_qubits"], scaling["mps_chi"]),
            )
        except Exception as e:  # lint: disable=broad-except(the agreement check annotates the perf point; its failure must not discard the measurement)
            p["agreement"] = {"error": f"{type(e).__name__}: {e}"}
    manifest = run_manifest(
        argv=["bench.py", "--scaling"],
        extra={"devices_on_model": scaling["devices_on_model"]},
    )
    non_dense = [
        p["n_qubits"]
        for p in scaling["points"]
        if p.get("quantum_impl") not in (None, "dense", "dense_fused")
    ]
    record = {
        "metric": "qsc_scaling_points",
        "value": len([p for p in scaling["points"] if "samples_per_sec" in p]),
        "unit": f"measured scaling points (of {len(scaling['points'])})",
        "platform": jax.default_backend(),
        "non_dense_points": non_dense,
        "details": {"qsc_scaling": scaling},
    }
    print(json.dumps(record), flush=True)
    if out_path:
        _write_telemetry_jsonl(out_path, manifest, record)
    return 0


def _bench_scenario_scaling(
    budget_s: float,
    s_values=None,
    batch: int | None = None,
    capacity_factor: float = 1.25,
    features: int = 16,
    table_path: str | None = None,
) -> dict:
    """The scenario-scaling axis (``scenario_scaling``): one measured point
    per S in the 3..64 grid — the routing dispatcher races dense-all-trunks
    vs capacity-bucketed sparse at that (S, batch) (``ops/dispatch_autotune``,
    same pattern as the qubit axis's impl race), the DISPATCHER's winner is
    timed as the routing-stage forward serving actually dispatches, and the
    point records rows/s, XLA cost, achieved roofline, the chosen mode, every
    candidate's timings, and a sparse-vs-dense value-agreement check — so the
    crossover table comes straight off the artifact.

    Candidate policy mirrors the qubit sweep: exclusions are RECORDED per
    point (sparse below its S >= 6 eligibility window carries the window
    reason — dense wins those points by construction, which is the committed
    proof that the reference grid keeps its dense path). The model geometry
    is reduced (16x8x4 pilots, ``features`` conv channels) so the S = 64
    dense candidate — deliberately ~S x the sparse work — stays timeable on
    the CPU harness; every S gates only against itself, so the reduced
    geometry never leaks into another axis's numbers."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from qdml_tpu.eval.sweep import (
        SCENARIO_SCALING_GRID,
        dispatch_agreement,
        scenario_batch,
    )
    from qdml_tpu.ops import dispatch_autotune as _da
    from qdml_tpu.ops.routing import expert_capacity
    from qdml_tpu.telemetry import cost as _cost
    from qdml_tpu.train.hdce import HDCE

    platform = jax.default_backend()
    if table_path:
        _da.set_table_path(table_path)
    hw = (8, 4)  # reduced n_sub x n_beam pilot image
    points = []
    for s in s_values or SCENARIO_SCALING_GRID:
        b = batch or scenario_batch(s)
        point: dict = {
            "n_scenarios": s,
            "batch": b,
            "capacity_factor": capacity_factor,
            "capacity": expert_capacity(b, s, capacity_factor),
            "candidates_raced": _da.eligible_modes(s),
        }
        try:
            rng = np.random.default_rng(0)
            model = HDCE(n_scenarios=s, features=features, out_dim=256)
            x = jnp.asarray(rng.standard_normal((b, *hw, 2)).astype(np.float32))
            vars_ = model.init(
                jax.random.PRNGKey(0),
                jnp.broadcast_to(x[None], (s,) + x.shape),
                train=False,
            )

            def apply_trunks(xs, _m=model, _v=vars_):
                return _m.apply(_v, xs, train=False)

            # force=True: the committed artifact's race timings must come
            # from THIS window, never a previous session's table entry
            entry = _da.ensure_route(
                apply_trunks,
                x,
                s,
                capacity_factor=capacity_factor,
                path=table_path,
                force=True,
                budget_s=budget_s,
            )
            winner = entry.get("best_infer")
            point["candidates"] = entry["candidates"]
            if entry.get("excluded"):
                point["excluded"] = entry["excluded"]
            if winner is None:
                point["error"] = "no candidate ran (see candidates.*.error)"
                points.append(point)
                continue
            point["dispatch"] = winner
            # the winner's routing-stage forward at this exact shape: the
            # point's number IS the race's own measurement when a race ran
            # (same timer, same shape — re-jitting a fresh closure would
            # compile and time the identical program a second time per
            # point); only window-only winners (never timed) pay a timing
            # window here. Cost comes from the lowering (traces, never
            # compiles).
            fn, args = _da.route_candidates(
                apply_trunks, x, s, capacity_factor
            )[winner]
            cost_rec = _cost.analyze_jit(fn, *args)
            raced_ms = (entry["candidates"].get(winner) or {}).get("infer_ms")
            if isinstance(raced_ms, (int, float)):
                ms = float(raced_ms)
            else:
                from qdml_tpu.quantum.autotune import _time_callable

                ms = _time_callable(fn, args, budget_s, 30)
            point["infer_ms"] = round(ms, 4)
            point["samples_per_sec"] = round(1e3 / ms * b, 1)
            point["cost"] = cost_rec
            point["roofline"] = _cost.achieved_roofline(cost_rec, 1e3 / ms)
            # batch >= S so the balanced leg touches EVERY expert (a
            # high-index packing defect must not hide behind a small
            # agreement batch at exactly the scale-out points)
            point["agreement"] = dispatch_agreement(
                s, batch=b, features=8, capacity_factor=capacity_factor
            )
        except Exception as e:  # lint: disable=broad-except(point isolation: one S failing must not kill the sweep's other points; the error is recorded on the point)
            point["error"] = f"{type(e).__name__}: {e}"
        points.append(point)
    return {
        "points": points,
        "platform": platform,
        "batch": batch,
        "capacity_factor": capacity_factor,
        "features": features,
        "image_hw": list(hw),
        "table": _da.table_path(table_path),
    }


def run_scenario_scaling_child(out_path: str | None = None) -> int:
    """The scenario-scaling sweep as its own child (``bench.py
    --scenario-scaling`` / ``scripts/scenario_scaling_sweep.py``): the S=64
    dense candidate is deliberately ~50x the sparse work, so the sweep never
    rides the default bench child's budget. Prints one JSON record; with
    ``out_path`` also writes the manifest-headed telemetry JSONL."""
    import jax

    from qdml_tpu.telemetry import run_manifest

    budget = float(os.environ.get("QDML_SCENARIO_BUDGET_S", "1.0"))
    table = os.environ.get("QDML_SCENARIO_TABLE") or None
    grid = os.environ.get("QDML_SCENARIO_GRID")  # "3,16" (tests); default full
    s_values = tuple(int(v) for v in grid.split(",")) if grid else None
    scaling = _bench_scenario_scaling(budget, s_values=s_values, table_path=table)
    manifest = run_manifest(argv=["bench.py", "--scenario-scaling"])
    sparse_points = [
        p["n_scenarios"] for p in scaling["points"] if p.get("dispatch") == "sparse"
    ]
    record = {
        "metric": "scenario_scaling_points",
        "value": len([p for p in scaling["points"] if "samples_per_sec" in p]),
        "unit": f"measured scaling points (of {len(scaling['points'])})",
        "platform": jax.default_backend(),
        "sparse_points": sparse_points,
        "details": {"scenario_scaling": scaling},
    }
    print(json.dumps(record), flush=True)
    if out_path:
        _write_telemetry_jsonl(out_path, manifest, record)
    return 0


def _bench_serve_infer(
    max_steps: int,
    budget_s: float,
    bucket: int = 64,
    batching: str = "bucket",
    fill: float = 1.0,
) -> dict:
    """Request-path throughput of the online serving engine
    (:mod:`qdml_tpu.serve`): one warmed ``infer`` per iteration — classify ->
    all-trunks -> top-1 route through a pre-compiled executable — i.e. the
    saturated-batcher steady state. Random-init params: serving cost is
    architecture-dependent, not weight-dependent. The record carries the
    zero-request-path-compile gate alongside the rate.

    ``batching``/``fill`` size the ragged variant (``serve_ragged_infer``):
    ``fill < 1`` serves a PARTIAL batch of ``ceil(fill * bucket)`` valid rows
    through the single capacity-tier executable — the production-fill regime
    the ragged mode targets — and the record reports goodput (valid rows/s,
    what ``samples_per_sec`` counts here) plus the padding-waste fraction, so
    the bucket-vs-ragged comparison in a bench session is apples-to-apples
    with the loadgen dryrun's columns."""
    import math

    import numpy as np

    from qdml_tpu.config import ExperimentConfig, ServeConfig, TrainConfig
    from qdml_tpu.serve import ServeEngine
    from qdml_tpu.telemetry import Histogram
    from qdml_tpu.train.hdce import init_hdce_state
    from qdml_tpu.train.qsc import init_sc_state

    cfg = ExperimentConfig(
        train=TrainConfig(batch_size=_CELL_BS, n_epochs=1),
        serve=ServeConfig(max_batch=bucket, buckets=(bucket,), batching=batching),
    )
    _, hdce_state = init_hdce_state(cfg, steps_per_epoch=100)
    hdce_vars = {"params": hdce_state.params, "batch_stats": hdce_state.batch_stats}
    _, sc_state = init_sc_state(cfg, quantum=False, steps_per_epoch=100)
    engine = ServeEngine(cfg, hdce_vars, {"params": sc_state.params})
    t0 = time.perf_counter()
    warm = engine.warmup()
    warmup_s = time.perf_counter() - t0
    n_valid = max(1, min(bucket, math.ceil(fill * bucket)))
    x = (
        np.random.default_rng(0)
        .standard_normal((n_valid, *cfg.image_hw, 2))
        .astype(np.float32)
    )
    # one probe sizes the loop (infer is synchronous: it device_gets results)
    t0 = time.perf_counter()
    _, _, _, info = engine.infer(x)
    est = max(time.perf_counter() - t0, 1e-4)
    n = max(3, min(max_steps, int(budget_s / est)))
    hist = Histogram()
    t0 = time.perf_counter()
    for _ in range(n):
        t1 = time.perf_counter()
        engine.infer(x)
        hist.add(time.perf_counter() - t1)
    wall = time.perf_counter() - t0
    # Phase decomposition columns (request tracing, docs/TELEMETRY.md):
    # measured AFTER the timed loop on a handful of traced dispatches — the
    # timed loop itself stays untraced, exactly the production trace_sample=0
    # fast path the rate above measures. compute = executable + device fence,
    # fetch = device->host reply copy, both host-clock durations off the
    # DispatchInfo, so a future serve_infer regression is attributable to the
    # phase that moved instead of one opaque batch_ms.
    ph_compute, ph_fetch = Histogram(), Histogram()
    for _ in range(min(n, 20)):
        *_rest, tinfo = engine.infer(x, traced=True)
        if tinfo.compute_s is not None:
            ph_compute.add(tinfo.compute_s)
        if tinfo.fetch_s is not None:
            ph_fetch.add(tinfo.fetch_s)
    rec = {
        # valid rows/s == goodput: padded rows never count, in either mode
        "samples_per_sec": round(n * n_valid / wall, 1),
        "goodput_rps": round(n * n_valid / wall, 1),
        "padding_waste": round(1.0 - n_valid / info.rows, 4),
        "bucket": bucket,
        "batching": info.mode,
        "n_valid": n_valid,
        "warmup_s": round(warmup_s, 3),
        "batch_ms": hist.summary(),
        "phases": {
            "compute": ph_compute.summary(),
            "fetch": ph_fetch.summary(),
        },
        "compile_cache_after_warmup": engine.request_path_compiles(),
        # the single bucket's COMPILED cost record (warmup holds the AOT
        # executable, so peak temp memory is available here)
        "cost": warm["cost"].get(str(bucket), {"available": False, "reason": "no bucket cost"}),
    }
    return rec


def _bench_error_entry(e: BaseException) -> dict:
    """Structured error entry for one failed sub-bench. A timed-loop
    transfer-guard trip (a steady-state device->host sync reintroduced under
    ``_timed_sps``'s strict guard) is additionally recorded as a COUNTED
    transfer (``host_transfers: 1``) so ``qdml-tpu report``'s host-transfer
    gate (current > baseline 0) fails CI on this row — sub-bench isolation
    keeps the other measurements, but this failure is structural, not a
    flaky tunnel, and must not degrade to an informational missing-metric
    row."""
    entry: dict = {"error": f"{type(e).__name__}: {e}"}
    msg = str(e).lower()
    if "transfer" in msg and ("guard" in msg or "device-to-host" in msg):
        entry["host_transfers"] = 1
    return entry


def run_child(platform: str) -> int:
    """Run every measurement, print one JSON dict to stdout."""
    import jax

    from qdml_tpu.telemetry import DivergenceError, run_manifest
    from qdml_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()

    if platform == "scaling":
        # the qubit-scaling sweep child (bench.py --scaling): its n=20+
        # compiles cost minutes on the CPU harness, so it never rides the
        # default child's budget — it IS the whole child here
        return run_scaling_child(os.environ.get("QDML_SCALING_OUT") or None)
    if platform == "scenario_scaling":
        # the scenario-scaling sweep child (bench.py --scenario-scaling):
        # the S=64 dense race entrant alone outweighs the default budget
        return run_scenario_scaling_child(
            os.environ.get("QDML_SCENARIO_OUT") or None
        )

    on_tpu = platform != "cpu"
    max_steps = 50 if on_tpu else 6
    budget = 120.0 if on_tpu else 60.0
    out: dict = {
        "backend": jax.default_backend(),
        "devices": len(jax.devices()),
        # device-topology/git/knob provenance; the parent lifts this into the
        # telemetry JSONL's header line
        "manifest": run_manifest(
            argv=["bench.py", "--child", platform],
            extra={"grid": list(_GRID), "cell_batch": _CELL_BS},
        ),
    }
    # Each sub-bench is independently guarded so one failing measurement
    # (flaky tunnelled backend, pallas unsupported off-TPU, ...) degrades to
    # an error entry instead of discarding the measurements that succeeded.
    scan_k = 16
    benches = [
        ("hdce_f32", lambda: _bench_hdce("float32", max_steps, budget)),
        ("hdce_bf16", lambda: _bench_hdce("bfloat16", max_steps, budget)),
    ]
    if on_tpu:
        # The scan-fused path exists to remove the per-step host dispatch gap
        # of the tunnelled accelerator; the CPU fallback is compute-bound
        # (~5 s per full-geometry step even after the r4 shift_matmul
        # lowering), so the K-step variant would only burn the child's
        # budget re-measuring the same compute.
        benches.append(
            ("hdce_bf16_scan", lambda: _bench_hdce_scan("bfloat16", scan_k, max_steps, budget))
        )
        benches.append(
            (
                "hdce_bf16_scan_rbg",
                lambda: _bench_hdce_scan(
                    "bfloat16", scan_k, max_steps, budget, rng_impl="rbg"
                ),
            )
        )
        # The generator-tail levers, stacked (r5 trace decomposition,
        # results/perf_r5/scan_rbg.trace.json.gz): hardware-RBG bits +
        # angle-split phase ramps — both algorithm-equivalent (same
        # distribution / same values to f32 rounding). Recorded next to the
        # default-stream scan; headline promotion is gated on the committed
        # alternating A/B (scripts/r5_scan_ab.py).
        benches.append(
            (
                "hdce_bf16_scan_fast",
                lambda: _bench_hdce_scan(
                    "bfloat16",
                    scan_k,
                    max_steps,
                    budget,
                    rng_impl="rbg",
                    trig_impl="split",
                ),
            )
        )
        # + bfloat16 Adam moments: halved optimizer-state HBM traffic on the
        # bandwidth-bound fused update. A documented OPTIMIZER deviation
        # (torch Adam carries f32 moments), so it never headlines; recorded
        # to quantify what the knob buys on real training runs.
        benches.append(
            (
                "hdce_bf16_scan_fast_bf16m",
                lambda: _bench_hdce_scan(
                    "bfloat16",
                    scan_k,
                    max_steps,
                    budget,
                    rng_impl="rbg",
                    trig_impl="split",
                    moments_dtype="bfloat16",
                ),
            )
        )
    benches += [
        ("qsc_dense", lambda: _bench_qsc("dense", max_steps, budget / 2)),
        # the gate-matrix-cached / layer-fused unitary build racing its
        # unfused twin above — the committed record proves (or disproves)
        # the fused build's win on this platform, per shape
        ("qsc_dense_fused", lambda: _bench_qsc("dense_fused", max_steps, budget / 2)),
        ("qsc_pallas", lambda: _bench_qsc("pallas", max_steps, budget / 2)),
        # the autotuned dispatcher path (quantum.impl=auto): tunes first,
        # then measures the step the table winner compiles into — the
        # acceptance gate is qsc_auto >= best fixed qsc_* (within noise),
        # and the record carries the winner + candidate timings
        ("qsc_auto", lambda: _bench_qsc("auto", max_steps, budget / 2, tune=True)),
        # the K=1 FUSED train path — what a default `train-qsc` run now
        # dispatches (scan_steps=1 runs under lax.scan with donated carry and
        # in-program synthesis since the dispatch-gap-elimination PR): tuned
        # dispatch, zero steady-state host transfers, roofline fraction in
        # the record. Compare against qsc_dense (the old fixed-batch
        # step-per-dispatch measurement) for the K=1 latency-floor story.
        ("qsc_k1_fused", lambda: _bench_qsc_scan("auto", 1, max_steps, budget / 2, tune=True)),
        # online-serving request path (inference only: cheap on both
        # platforms) — the steady-state rate `qdml-tpu serve` sustains with
        # a saturated batcher, plus its zero-compile gate
        ("serve_infer", lambda: _bench_serve_infer(max_steps, budget / 4)),
        # the ragged twin at a production (3/4) fill level: the traced
        # valid-count executable serving a partial batch — goodput and
        # padding-waste columns match the loadgen dryrun's, so a bench
        # session carries the bucket-vs-ragged per-dispatch comparison too
        (
            "serve_ragged_infer",
            lambda: _bench_serve_infer(
                max_steps, budget / 4, batching="ragged", fill=0.75
            ),
        ),
    ]
    if on_tpu:
        # The QSC K=1 step is ~entirely host dispatch gap at this model size
        # (<1% MFU); the scan-fused variant is the training throughput a real
        # `train-qsc --train.scan_steps=16` run achieves.
        benches.append(
            (
                "qsc_dense_scan",
                lambda: _bench_qsc_scan("dense", scan_k, max_steps, budget / 2),
            )
        )
    for key, fn in benches:
        try:
            out[key] = fn()
        except DivergenceError as e:
            # typed divergence keeps its flight-recorder pointer in the
            # artifact instead of being flattened into a generic error string
            out[key] = {
                "error": f"DivergenceError: {e}",
                "diverged": True,
                "flightrec_dump": e.dump_dir,
            }
        except Exception as e:  # lint: disable=broad-except(sub-bench isolation: one failing sub-bench must not kill the others; DivergenceError is handled above)
            out[key] = _bench_error_entry(e)
    from qdml_tpu.utils.compile_cache import compile_cache_stats

    out["compile_cache"] = compile_cache_stats()
    print(json.dumps(out), flush=True)
    return 0


# ---------------------------------------------------------------------------
# Torch-CPU reference baseline (the Runner...py:181-204 pattern)
# ---------------------------------------------------------------------------


def measure_torch_cpu_reference(n_steps: int = 2) -> float | None:
    """Reference-equivalent training step in torch on CPU: 3 trunks + shared
    head, NINE sequential (loss/9).backward() calls per step, 4 Adam
    optimizers — the only hardware torch can use in this image."""
    try:
        import torch
        import torch.nn as nn
    except ImportError:
        return None
    torch.manual_seed(0)

    def trunk():
        layers: list = []
        ch = 2
        for _ in range(3):
            layers += [
                nn.Conv2d(ch, 32, 3, padding=1, bias=False),
                nn.BatchNorm2d(32),
                nn.ReLU(inplace=True),
            ]
            ch = 32
        return nn.Sequential(*layers)

    convs = [trunk() for _ in range(3)]
    head = nn.Linear(32 * 16 * 8, 2048)
    opts = [torch.optim.Adam(c.parameters(), lr=1e-3) for c in convs]
    opts.append(torch.optim.Adam(head.parameters(), lr=1e-3))
    crit = lambda a, b: torch.sum((a - b) ** 2) / torch.sum(b**2)  # noqa: E731

    x = torch.randn(3, 3, _CELL_BS, 2, 16, 8)
    y = torch.randn(3, 3, _CELL_BS, 2048)
    t0 = 0.0
    for it in range(n_steps + 1):  # one warmup step
        if it == 1:
            t0 = time.perf_counter()
        for o in opts:
            o.zero_grad()
        for si in range(3):
            for ui in range(3):
                feats = convs[si](x[si, ui]).flatten(1)
                loss = crit(head(feats), y[si, ui]) / 9.0
                loss.backward()
        for o in opts:
            o.step()
    dt = time.perf_counter() - t0
    return n_steps * 9 * _CELL_BS / dt


# ---------------------------------------------------------------------------
# Parent: probe, retry, fall back, assemble the one-line record
# ---------------------------------------------------------------------------

# The probe prints backend:result so a silent JAX CPU fallback (e.g. axon
# plugin not registered) cannot masquerade as a TPU run. It warms the
# persistent compile cache so a healthy tunnel answers in seconds.
_PROBE = (
    "from qdml_tpu.utils.compile_cache import enable_compile_cache; "
    "enable_compile_cache(); "
    "import jax, jax.numpy as jnp; "
    "print(jax.default_backend(), int(jnp.ones((8, 8)).sum()))"
)


def _cpu_env() -> dict:
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""  # skip axon PJRT registration entirely
    env["JAX_PLATFORMS"] = "cpu"
    return env


# Timestamped log of every probe attempt this harness run, summarized into
# the final record as ``probe_attempts`` — a cpu_fallback artifact thereby
# PROVES the tunnel was down across the whole window instead of asserting it
# (VERDICT r3 ask #5). ``t`` is seconds since harness start. BENCH_r05 showed
# the raw list degenerating into a retry storm's paper trail (10 identical
# "probe timed out" tails over ~1000s), so the artifact now carries ONE
# structured summary (attempts, window, outcome counts, first/last) instead
# of the repeated tails — see summarize_probe_log().
PROBE_LOG: list[dict] = []
_T0 = time.monotonic()


def summarize_probe_log() -> dict:
    """Compact structured view of PROBE_LOG for the final record: attempt
    count, probing window, cumulative time spent inside probe subprocesses,
    and per-outcome counts (a flapping tunnel shows its distinct failure
    modes once each, with counts, not as N copies of the same tail)."""
    outcomes: dict[str, int] = {}
    for p in PROBE_LOG:
        outcomes[p["result"]] = outcomes.get(p["result"], 0) + 1
    if not PROBE_LOG:
        return {"attempts": 0, "outcomes": outcomes}
    return {
        "attempts": len(PROBE_LOG),
        "window_s": round(PROBE_LOG[-1]["t"] - PROBE_LOG[0]["t"], 1),
        "outcomes": outcomes,
        "first": PROBE_LOG[0],
        "last": PROBE_LOG[-1],
    }


def probe_unavailable_outcome(budget_s: float, spent_s: float) -> dict | None:
    """The single structured ``probe_unavailable`` record for artifacts that
    never reached the TPU: None when any probe succeeded."""
    if any(p["result"] == "ok" for p in PROBE_LOG):
        return None
    return {
        **summarize_probe_log(),
        "probe_budget_s": round(budget_s, 1),
        "probe_spent_s": round(spent_s, 1),
    }


def _probe_timeouts() -> tuple[int, int]:
    """(cheap_s, full_s) — the two probe-timeout tiers, single-sourced for
    probe_tpu's up-front schedule and main()'s late loop."""
    full = int(os.environ.get("QDML_BENCH_PROBE_TIMEOUT", "150"))
    cheap = min(int(os.environ.get("QDML_BENCH_PROBE_TIMEOUT_CHEAP", "45")), full)
    return cheap, full


def _probe_once_tiered(i: int) -> str | None:
    """One probe at the tier the attempt index selects: cheap, with every
    4th escalated to the full timeout (slow-but-live tunnel)."""
    cheap_s, full_s = _probe_timeouts()
    return _probe_once(full_s if i % 4 == 3 else cheap_s)


def _probe_once(timeout_s: int) -> str | None:
    """One probe subprocess; returns None on a verified-TPU success. Every
    attempt (outcome + timestamp + timeout used) is appended to PROBE_LOG."""
    t = round(time.monotonic() - _T0, 1)
    err: str | None
    try:
        # cwd = repo root so the '-c' child resolves qdml_tpu regardless
        # of where the harness itself was invoked from (python -c puts
        # the cwd, not the script dir, on sys.path).
        r = subprocess.run(
            [sys.executable, "-c", _PROBE],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        err = f"probe timed out after {timeout_s}s (backend init hang)"
    else:
        if r.returncode == 0 and r.stdout.strip().endswith("64"):
            # parse the probe's OWN output line (the last one): earlier stdout
            # noise from plugin imports must not defeat the backend check
            backend = r.stdout.strip().splitlines()[-1].split()[0]
            err = (
                None
                if backend != "cpu"
                else f"jax silently fell back to backend {backend!r}"
            )
        else:
            lines = (r.stderr.strip() or r.stdout.strip()).splitlines()
            # prefer the actual exception line over jax's trailing filter notes
            err_lines = [ln for ln in lines if "Error" in ln or "error" in ln]
            err = (err_lines or lines or ["rc!=0"])[-1].strip()
    PROBE_LOG.append(
        {"t": t, "timeout_s": timeout_s, "result": "ok" if err is None else err}
    )
    return err


def probe_tpu(attempts: int | None = None, timeout_s: int | None = None) -> str | None:
    """Returns None if a TPU subprocess computes successfully, else the error.

    The tunnelled axon backend drops and restores on minutes-to-tens-of-
    minutes timescales (two rounds of driver artifacts show a 2-attempt
    probe losing the race; a round-3 session observed a >25-minute outage),
    so probing is patient AND spread: cheap attempts up front, then the
    CPU fallback bench burns ~10 further minutes, then continuous cheap
    probes for as long as the QDML_BENCH_WALL_BUDGET_S wall budget leaves
    room to still run the TPU bench child (see main) — before conceding a
    cpu_fallback record.

    Two-tier timeouts (VERDICT r3 ask #5 — the old flat 150s probe bought
    only ~6 attempts across the window): a DOWN tunnel hangs at backend
    init, and a HEALTHY one with the warmed persistent compile cache
    answers in well under QDML_BENCH_PROBE_TIMEOUT_CHEAP (45s), so most
    attempts use the cheap timeout and every 4th escalates to the full
    QDML_BENCH_PROBE_TIMEOUT (150s) to keep catching a live-but-slow
    tunnel (cold cache, loaded host). The liveness check IS the real
    resource check — it computes on the device — just time-bounded.
    """
    attempts = attempts or int(os.environ.get("QDML_BENCH_PROBE_ATTEMPTS", "3"))
    cheap_env, full_env = _probe_timeouts()
    timeout_s = timeout_s or full_env
    cheap_s = min(cheap_env, timeout_s)
    err = "unknown"
    for i in range(attempts):
        if i:
            backoff = min(20 * 2 ** (i - 1), 300)
            print(f"[bench] TPU probe retry in {backoff}s", file=sys.stderr, flush=True)
            time.sleep(backoff)
        # escalate to the full timeout on the last of the up-front attempts
        # and on every 4th attempt of a longer schedule
        full = i == attempts - 1 if attempts <= 4 else i % 4 == 3
        err = _probe_once(timeout_s if full else cheap_s)
        if err is None:
            return None
    return err


def _run_bench_child(env: dict, platform: str, timeout_s: int) -> dict | None:
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child", platform],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            env=env,
        )
    except subprocess.TimeoutExpired:
        print(f"[bench] {platform} child timed out", file=sys.stderr, flush=True)
        return None
    if r.returncode != 0:
        tail = "\n".join(r.stderr.splitlines()[-8:])
        print(f"[bench] {platform} child failed:\n{tail}", file=sys.stderr, flush=True)
        return None
    for line in reversed(r.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue
    return None


def _latest_committed_tpu_record() -> dict | None:
    """Pointer to the newest committed on-chip record (by mtime), attached to
    every non-TPU artifact so it always carries a path to real TPU evidence —
    observed tunnel outages exceed an hour while the probe schedule spans
    ~25 minutes. Never raises: a missing results/ dir or unreadable file
    degrades to None/path-only."""
    try:
        here = os.path.dirname(os.path.abspath(__file__))
        rdir = os.path.join(here, "results")
        cands = [
            f
            for f in os.listdir(rdir)
            if f.startswith("bench_tpu_") and f.endswith(".json")
        ]
        if not cands:
            return None
        newest = max(cands, key=lambda f: os.path.getmtime(os.path.join(rdir, f)))
        path = os.path.join("results", newest)
        try:
            with open(os.path.join(rdir, newest)) as fh:
                rec = json.load(fh)
            return {
                "path": path,
                "value": rec.get("value"),
                "platform": rec.get("platform"),
                "mfu": rec.get("mfu"),
            }
        except (OSError, json.JSONDecodeError):
            return {"path": path}
    except OSError:
        return None


def _write_telemetry_jsonl(path: str, manifest: dict | None, record: dict) -> None:
    """Write the bench artifact as a telemetry JSONL: run-manifest header
    line (the child's device-topology manifest, or a host-only one when no
    child produced one) + the record. Never raises — telemetry must not be
    able to kill a bench run that already has a result to report."""
    try:
        if manifest is None:
            # parent-side fallback; include_jax=False keeps the parent's
            # never-imports-jax robustness contract intact
            from qdml_tpu.telemetry import run_manifest

            manifest = run_manifest(argv=["bench.py"], include_jax=False)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as fh:
            fh.write(json.dumps(manifest) + "\n")
            fh.write(json.dumps({"kind": "bench_record", **record}) + "\n")
    except Exception as e:  # lint: disable=broad-except(bench telemetry write is best-effort — the result was already printed; a write failure must not kill a finished bench)
        print(f"[bench] telemetry write failed: {e}", file=sys.stderr, flush=True)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--child", default=None)
    ap.add_argument(
        "--out",
        default=os.environ.get("QDML_BENCH_TELEMETRY_OUT") or None,
        help="telemetry JSONL path (manifest header + record); the one-line "
        "stdout record is unchanged",
    )
    ap.add_argument(
        "--scaling",
        action="store_true",
        help="run the n=4..24 qubit-scaling sweep child (qsc_scaling record) "
        "instead of the standard bench — honors the caller's JAX_PLATFORMS/"
        "XLA_FLAGS topology (scripts/qubit_scaling_sweep.py forces the "
        "8-virtual-device CPU harness)",
    )
    ap.add_argument(
        "--scenario-scaling",
        action="store_true",
        help="run the S=3..64 scenario-scaling sweep child (scenario_scaling "
        "record: per-S dense-vs-sparse dispatch race + XLA cost) instead of "
        "the standard bench (scripts/scenario_scaling_sweep.py forces the "
        "8-virtual-device CPU harness)",
    )
    args = ap.parse_args()
    if args.child:
        return run_child(args.child)
    if args.scenario_scaling:
        env = dict(os.environ)
        if args.out:
            env["QDML_SCENARIO_OUT"] = args.out
        timeout = int(os.environ.get("QDML_SCENARIO_TIMEOUT_S", "3600"))
        d = _run_bench_child(env, "scenario_scaling", timeout_s=timeout)
        if d is None:
            print(json.dumps({"metric": "scenario_scaling_points", "value": None,
                              "error": "scenario-scaling child failed or timed out"}))
            return 1
        print(json.dumps(d))
        return 0
    if args.scaling:
        env = dict(os.environ)
        if args.out:
            env["QDML_SCALING_OUT"] = args.out
        timeout = int(os.environ.get("QDML_SCALING_TIMEOUT_S", "3600"))
        d = _run_bench_child(env, "scaling", timeout_s=timeout)
        if d is None:
            print(json.dumps({"metric": "qsc_scaling_points", "value": None,
                              "error": "scaling child failed or timed out"}))
            return 1
        print(json.dumps(d))
        return 0

    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")
    peak = _PEAK_BF16.get(gen, _PEAK_BF16["v5e"])

    def try_tpu_bench(timeout_s: int = 1500) -> tuple[dict | None, str | None]:
        """(details, error): TPU measurements, or why there are none."""
        d = _run_bench_child(dict(os.environ), "tpu", timeout_s=timeout_s)
        if d is None:
            return None, "tpu bench child failed or timed out after a good probe"
        if d.get("backend") == "cpu":
            # belt-and-braces: never label CPU numbers as TPU throughput/MFU
            return None, "bench child ran on the cpu backend despite a tpu probe"
        return d, None

    t_start = time.monotonic()
    # Soft wall-clock budget for the whole harness (the last TPU child may
    # overshoot it — see the loop). Observed tunnel outages run tens of
    # minutes while a fixed two-round probe schedule spans ~14; the budgeted
    # loop below keeps probing for as long as there is still time to run the
    # TPU bench child before the budget ends, so the record goes tpu-* the
    # moment the tunnel comes back anywhere inside the window.
    wall_budget = int(os.environ.get("QDML_BENCH_WALL_BUDGET_S", "1800"))
    # Conservative estimate of a warm-cache TPU bench child (backend init
    # over the tunnel + per-bench compiles + 50-step measurements).
    tpu_child_cost = int(os.environ.get("QDML_BENCH_TPU_CHILD_BUDGET_S", "700"))

    # Total probe budget: cumulative wall time allowed INSIDE probe
    # subprocesses across the whole harness run. BENCH_r05's retry storm
    # (10 identical "probe timed out" attempts burning ~1000s of a down-all-
    # window tunnel) is what this caps: a hanging tunnel eats its timeout on
    # every attempt, so attempts x timeout must be bounded by policy, not by
    # the wall clock happening to run out.
    probe_budget = float(os.environ.get("QDML_BENCH_PROBE_BUDGET_S", "600"))
    probe_spent = 0.0

    t_probe = time.monotonic()
    tpu_error = probe_tpu()
    probe_spent += time.monotonic() - t_probe
    details: dict | None = None
    platform = None
    if tpu_error is None:
        details, tpu_error = try_tpu_bench()
        platform = f"tpu-{gen}"
    if details is None:
        details = _run_bench_child(_cpu_env(), "cpu", timeout_s=1500)
        platform = "cpu_fallback"
        # Budgeted TPU re-attempts: the CPU bench just banked a fallback
        # record; late probes now back off EXPONENTIALLY (60s -> 120 -> 240
        # -> 480, capped) instead of the old ~once-a-minute cadence, and stop
        # when either the cumulative probe budget or the wall window (minus
        # a TPU child's cost) runs out. At least ONE late probe always runs
        # even if the earlier phases overran the window (the pre-loop worst
        # case can already exceed it), so this path is never weaker than the
        # old unconditional last-chance retry. A late TPU record always
        # supersedes the CPU fallback. Probe timeouts honor
        # QDML_BENCH_PROBE_TIMEOUT (probe_tpu's env default).
        first = True
        late_i = 0
        while first or (
            probe_spent < probe_budget
            and time.monotonic() - t_start < wall_budget - tpu_child_cost
        ):
            # The guaranteed first pass keeps the old multi-attempt backoff
            # spread (env default); later passes are single cheap probes with
            # every 4th escalated to the full timeout (slow-but-live tunnel).
            t_probe = time.monotonic()
            if first:
                ok = probe_tpu() is None
            else:
                ok = _probe_once_tiered(late_i) is None
                late_i += 1
            probe_spent += time.monotonic() - t_probe
            first = False
            if ok:
                # Cap the child near the remaining budget, but never below
                # the old fixed 1500s: a child recovering from a long outage
                # is the cold-compile case, and a TPU record is worth
                # overshooting the (soft) wall budget for.
                left = wall_budget - (time.monotonic() - t_start)
                late, late_err = try_tpu_bench(timeout_s=max(1500, int(left)))
                if late is not None:
                    details, tpu_error, platform = late, None, f"tpu-{gen}"
                elif tpu_error is None:
                    tpu_error = late_err
                break  # good probe: the child ran (or conclusively failed)
            left = wall_budget - tpu_child_cost - (time.monotonic() - t_start)
            if left <= 0 or probe_spent >= probe_budget:
                break
            # exponential backoff between late probes, capped at 8 minutes:
            # a down-all-window tunnel costs a handful of attempts, not a
            # storm of them (BENCH_r05: 10 tails), while a brief flap is
            # still caught within the first couple of minutes
            backoff = min(60.0 * 2**late_i, 480.0)
            print(
                f"[bench] tunnel still down ({probe_spent:.0f}s of "
                f"{probe_budget:.0f}s probe budget spent, {left:.0f}s of "
                f"window left); next probe in {backoff:.0f}s",
                file=sys.stderr,
                flush=True,
            )
            time.sleep(min(backoff, max(1.0, left)))
    child_manifest = details.pop("manifest", None) if details else None
    probe_down = probe_unavailable_outcome(probe_budget, probe_spent)
    if details is None:
        rec = {
            "metric": "hdce_train_samples_per_sec_per_chip",
            "value": None,
            "unit": "samples/sec (3x3 DML grid train step, cell batch 256)",
            "vs_baseline": None,
            "platform": "none",
            "error": tpu_error or "all bench children failed",
            "probe_attempts": summarize_probe_log(),
        }
        if probe_down is not None:
            rec["probe_unavailable"] = probe_down
        committed = _latest_committed_tpu_record()
        if committed is not None:
            rec["latest_committed_tpu_record"] = committed
        print(json.dumps(rec))
        if args.out:
            _write_telemetry_jsonl(args.out, child_manifest, rec)
        return 1

    baseline_live = measure_torch_cpu_reference()
    # MFU vs the generation's bf16 peak (conservative for the f32 run). Only
    # meaningful on the TPU; CPU fallback reports null.
    on_tpu = platform != "cpu_fallback"
    for d in details.values():
        if isinstance(d, dict) and "model_tflops" in d:
            d["mfu"] = round(d["model_tflops"] * 1e12 / peak, 4) if on_tpu else None

    # Headline: the framework's intended fast path — bf16 activations on the
    # MXU with scan-fused dispatch (what train.scan_steps=K runs) — when on
    # TPU; the reference-dtype f32 step on the CPU fallback. The dtype is
    # part of the record so the two are never conflated. The headline KEY is
    # fixed (default-config threefry scan) so value/vs_baseline stay
    # comparable across rounds; the rbg-generator scan variant is recorded
    # in details and only headlines as a fallback when the default-stream
    # measurement itself errored. (Promoting rbg to the headline is a code
    # change backed by a committed alternating A/B, not a per-run max of
    # two noisy single measurements.)
    order = (
        (
            "hdce_bf16_scan",
            "hdce_bf16_scan_rbg",
            "hdce_bf16_scan_fast",
            "hdce_bf16",
            "hdce_f32",
        )
        if on_tpu
        else ("hdce_f32", "hdce_bf16")
    )
    key = next(
        (k for k in order if "samples_per_sec" in details.get(k, {})), None
    )
    if key is None:
        rec = {
            "metric": "hdce_train_samples_per_sec_per_chip",
            "value": None,
            "unit": "samples/sec (3x3 DML grid train step, cell batch 256)",
            "vs_baseline": None,
            "platform": platform,
            "error": "all HDCE measurements failed",
            "details": details,
            "probe_attempts": summarize_probe_log(),
        }
        if probe_down is not None:
            rec["probe_unavailable"] = probe_down
        committed = _latest_committed_tpu_record()
        if committed is not None:
            rec["latest_committed_tpu_record"] = committed
        print(json.dumps(rec))
        if args.out:
            _write_telemetry_jsonl(args.out, child_manifest, rec)
        return 1
    dtype = {
        "hdce_bf16": "bfloat16",
        "hdce_bf16_scan": "bfloat16",
        "hdce_bf16_scan_rbg": "bfloat16",
        "hdce_bf16_scan_fast": "bfloat16",
        "hdce_f32": "float32",
    }[key]
    headline = details[key]
    value = headline["samples_per_sec"]
    scan_note = (
        f", {headline['scan_steps']}-step fused dispatch"
        if "scan_steps" in headline
        else ""
    )
    if key == "hdce_bf16_scan_rbg":
        scan_note += ", hardware-RBG generator"
    elif key == "hdce_bf16_scan_fast":
        scan_note += ", hardware-RBG generator, angle-split trig"
    committed_tpu = None if platform != "cpu_fallback" else _latest_committed_tpu_record()

    record = {
        "metric": "hdce_train_samples_per_sec_per_chip",
        "value": value,
        "unit": f"samples/sec (3x3 DML grid train step, cell batch 256, {dtype}{scan_note})",
        # Fixed committed constant (round-2 driver host) — comparable across
        # rounds; the live same-host measurement is context only.
        "vs_baseline": round(value / REFERENCE_TORCH_CPU_SPS, 2),
        # Same-window ratio against the live torch measurement: on the 1-core
        # driver host every CPU number scales ~1:1 with whatever else the
        # host is running, so cross-round comparisons of fallback sps compare
        # contention, not code. This ratio cancels the contention (both
        # sides measured in the same window) and is the number to watch on a
        # cpu_fallback record; r4's apparent 206-vs-451 regression was
        # exactly this (0.28 live-ratio in the contended bench window vs
        # 0.30 in the uncontended profile — the code was identical).
        # cpu_fallback only: on a TPU record the headline is measured on the
        # TPU VM while the torch baseline runs on the driver host — a
        # cross-host ratio has no same-window meaning.
        "vs_baseline_live": (
            round(value / baseline_live, 2)
            if baseline_live and platform == "cpu_fallback"
            else None
        ),
        "platform": platform,
        "dtype": dtype,
        "mfu": headline.get("mfu"),
        "torch_cpu_reference_sps": REFERENCE_TORCH_CPU_SPS,
        "torch_cpu_reference_sps_live": round(baseline_live, 1) if baseline_live else None,
        "details": details,
        "probe_attempts": summarize_probe_log(),
    }
    if probe_down is not None:
        # single structured outcome for the whole failed probe campaign —
        # the repeated-tails storm of BENCH_r05 collapses to one record
        record["probe_unavailable"] = probe_down
    if tpu_error is not None:
        record["tpu_error"] = tpu_error
    if committed_tpu is not None:
        record["latest_committed_tpu_record"] = committed_tpu
    if platform == "cpu_fallback":
        # Why this number trails the torch-CPU baseline (VERDICT r3 ask #7),
        # measured in results/perf_r4/cpu_fallback_profile.json: XLA:CPU's
        # gradient kernels for BATCHED convs (what the vmapped per-scenario
        # trunks lower to) run 23x slower than the identical work unbatched,
        # while its plain conv/matmul kernels sit within ~2x of torch. The
        # framework now lowers convs to shifted matmuls off-TPU
        # (ModelConfig.conv_impl "auto", models/cnn.py — the details'
        # conv_impl field records engagement), lifting the fallback step
        # 172 -> 451 sps uncontended; the remaining ~3x is torch's fused
        # oneDNN kernels vs XLA:CPU's emission at these tiny 16x8 spatial
        # shapes — a CPU code-path quality issue, no bearing on the TPU
        # design. Absolute fallback sps (HDCE and QSC alike) scales with
        # driver-host contention (1 core); vs_baseline_live is the
        # contention-cancelled ratio. bf16 trailing f32 here is expected:
        # XLA:CPU emulates bf16, the MXU fast path is TPU-only.
        record["cpu_fallback_note"] = (
            "XLA:CPU batched-conv gradients are the cliff (23x vs the same "
            "work unbatched); convs lower to shift_matmul off-TPU since r4 "
            "(172 -> 451 sps uncontended, engagement recorded in "
            "details.*.conv_impl) — see "
            "results/perf_r4/cpu_fallback_profile.json. Fallback sps scales "
            "with driver-host contention; compare vs_baseline_live across "
            "rounds, not raw sps (r4: 206/729 live = 0.28 contended vs the "
            "profile's 451/1515 = 0.30 uncontended — same code). bf16 < f32 "
            "on CPU is expected (no bf16 fast path off-TPU)."
        )
    print(json.dumps(record))
    if args.out:
        _write_telemetry_jsonl(args.out, child_manifest, record)
    return 0


if __name__ == "__main__":
    sys.exit(main())
