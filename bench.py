#!/usr/bin/env python
"""Benchmark: HDCE DML train-step throughput (samples/sec/chip) on real TPU.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The measured quantity is the full fused HDCE training step over the 3x3
scenario/user grid at the reference batch size (256 per cell => 2304 samples
per step; reference loop at ``Runner_P128_QuantumNAT_onchipQNN.py:181-204``).

``vs_baseline`` is the speedup over a faithful torch-CPU implementation of the
reference's training step (three Conv_P128 trunks + shared FC_P128 head, nine
sequential (loss/9).backward() calls per step), measured in-process on this
host. The reference's own hardware baseline is unpublished (SURVEY.md §6);
BASELINE.md's target is >= 3x a single V100.
"""

from __future__ import annotations

import json
import sys
import time


def measure_tpu(n_steps: int = 50, cell_bs: int = 256) -> float:
    import jax
    import jax.numpy as jnp

    from qdml_tpu.config import DataConfig, ExperimentConfig, TrainConfig
    from qdml_tpu.data.channels import ChannelGeometry
    from qdml_tpu.data.datasets import make_network_batch
    from qdml_tpu.train.hdce import init_hdce_state, make_hdce_train_step

    cfg = ExperimentConfig(
        data=DataConfig(), train=TrainConfig(batch_size=cell_bs, n_epochs=1)
    )
    geom = ChannelGeometry.from_config(cfg.data)
    s, u = cfg.data.n_scenarios, cfg.data.n_users
    scen = jnp.broadcast_to(jnp.arange(s)[:, None, None], (s, u, cell_bs))
    user = jnp.broadcast_to(jnp.arange(u)[None, :, None], (s, u, cell_bs))
    idx = jnp.broadcast_to(jnp.arange(cell_bs)[None, None, :], (s, u, cell_bs))
    batch = make_network_batch(
        jnp.uint32(0), scen, user, idx, jnp.float32(cfg.data.snr_db), geom
    )
    batch = {k: batch[k] for k in ("yp_img", "h_label", "h_perf")}

    model, state = init_hdce_state(cfg, steps_per_epoch=100)
    step = make_hdce_train_step(model, state.tx)
    for _ in range(3):  # warmup + compile
        state, m = step(state, batch)
    float(m["loss"])  # host transfer forces execution (block_until_ready is
    # not sufficient on tunnelled backends)
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, m = step(state, batch)
    float(m["loss"])
    dt = time.perf_counter() - t0
    return n_steps * s * u * cell_bs / dt


def measure_torch_cpu_reference(n_steps: int = 2, cell_bs: int = 256) -> float | None:
    """Reference-equivalent training step in torch on CPU (the only hardware
    in this image torch can use): 3 trunks + shared head, 9 sequential
    backwards per step, 4 Adam optimizers — the Runner...py:181-204 pattern."""
    try:
        import torch
        import torch.nn as nn
    except ImportError:
        return None
    torch.manual_seed(0)

    def trunk():
        layers = []
        ch = 2
        for _ in range(3):
            layers += [
                nn.Conv2d(ch, 32, 3, padding=1, bias=False),
                nn.BatchNorm2d(32),
                nn.ReLU(inplace=True),
            ]
            ch = 32
        return nn.Sequential(*layers)

    convs = [trunk() for _ in range(3)]
    head = nn.Linear(32 * 16 * 8, 2048)
    opts = [torch.optim.Adam(c.parameters(), lr=1e-3) for c in convs]
    opts.append(torch.optim.Adam(head.parameters(), lr=1e-3))
    crit = lambda a, b: torch.sum((a - b) ** 2) / torch.sum(b**2)  # noqa: E731

    x = torch.randn(3, 3, cell_bs, 2, 16, 8)
    y = torch.randn(3, 3, cell_bs, 2048)
    # one warmup step
    for it in range(n_steps + 1):
        if it == 1:
            t0 = time.perf_counter()
        for o in opts:
            o.zero_grad()
        for si in range(3):
            for ui in range(3):
                feats = convs[si](x[si, ui]).flatten(1)
                loss = crit(head(feats), y[si, ui]) / 9.0
                loss.backward()
        for o in opts:
            o.step()
    dt = time.perf_counter() - t0
    return n_steps * 9 * cell_bs / dt


def main() -> int:
    value = measure_tpu()
    baseline = measure_torch_cpu_reference()
    vs = value / baseline if baseline else None
    print(
        json.dumps(
            {
                "metric": "hdce_train_samples_per_sec_per_chip",
                "value": round(value, 1),
                "unit": "samples/sec (3x3 DML grid train step, cell batch 256)",
                "vs_baseline": round(vs, 2) if vs else None,
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
