#!/bin/bash
# Training launcher for the sigma dose-response study
# (results/noise_robustness/sigma_sweep/): ONE vmapped noise-sweep ensemble
# run (every sigma in quantum.noise_sweep trained simultaneously), then the
# per-member trajectory-noise evaluation. Run at the default config; the
# nat_sweep preset is equivalent for this study since the grad_prune
# measurement (results/noise_robustness/grad_prune/) led to pruning being
# removed from it.
set -e
cd /root/repo
mkdir -p runs
python -m qdml_tpu.cli nat-sweep --train.n_epochs=30 --train.resume=true \
    --train.workdir=runs/nr_sweep > runs/nr_sweep.log 2>&1
python scripts/r3_sigma_robustness.py
echo "SIGMA ROBUSTNESS DONE"
