#!/bin/bash
# Reduced-DATA protocol, doubled training: extend the round-4 CPU control
# study (30 epochs x 4k/cell, runs/science_cpu) to 60 epochs by resume.
#
# Question under test: results/dce/PROTOCOL.md attributes the learned
# estimators' below-MMSE tail at 13-15 dB SNR to the REDUCED protocol
# ("the reduced training leaves them below MMSE") — an assertion, not a
# measurement. Doubling epochs at the same 4k/cell data separates the two
# reduction axes: if the 13-15 dB tail closes toward MMSE at 60 epochs,
# the shortfall was training length; if it persists, it is data volume.
# Also re-measures the HDCE-vs-DCE hierarchy gain at 60 epochs (does the
# architectural ordering survive longer training?).
#
# Writes results/dce/epochs60/ — results/dce/ itself stays the 30-epoch
# protocol the committed PROTOCOL.md describes (reduced30ep/ holds the
# backup). Resume-capable; safe to re-run. The quantum classifier is not
# extended (the gap under measurement is DCE-vs-HDCE; eval degrades
# gracefully, Test.py:81-86 semantics).
set -e
cd /root/repo
# Optional seed arg: `r5_dce_epochs60.sh 2` extends the seed-2 study
# (runs/science_cpu_s2, the r4_dce_seeds.sh seed convention) so the
# gain-widening finding can meet the repo's 3-seed README standard.
S=${1:-}
if [ -n "$S" ]; then
  WD=runs/science_cpu_s$S
  SEEDS="--train.seed=$S --data.seed=$((2026 + S))"
  OUT=results/dce/epochs60/seed$S
else
  WD=runs/science_cpu
  SEEDS=""
  OUT=results/dce/epochs60
fi
RED="--data.data_len=4000 --train.n_epochs=60"
for cmd in train-hdce train-sc train-dce; do
  echo "=== $cmd (REDUCED data, 60 epochs, resume from 30, seed=${S:-default}) ==="
  python -m qdml_tpu.cli $cmd $RED $SEEDS --train.workdir=$WD --train.resume=true
done
python -m qdml_tpu.cli eval --data.data_len=4000 --train.workdir=$WD \
    --eval.results_dir=$OUT
cp $WD/Pn_128/*/eval.metrics.jsonl $OUT/ 2>/dev/null || true
# never clobber an existing PROTOCOL.md — findings get appended to it
if [ ! -f $OUT/PROTOCOL.md ]; then
  cat > $OUT/PROTOCOL.md <<'EOF'
# Protocol: 4k samples/cell (reduced), 60 epochs (2x the reduced runs)

Same training data volume as the 30-epoch reduced-protocol study, twice
the epochs, trained by resuming the same checkpoints
(`scripts/r5_dce_epochs60.sh`). Separates the two axes of the round-4
protocol reduction: epochs vs data volume.
EOF
fi
echo "DCE EPOCHS60 DONE (seed=${S:-default})"
