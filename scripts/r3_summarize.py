"""Assemble results/ablation and results/robust multi-seed summaries.

Run after scripts/r3_ablation.sh and scripts/r3_multiseed.sh complete:
    PYTHONPATH=/root/repo python scripts/r3_summarize.py
"""

import json
import os

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(path):
    with open(os.path.join(ROOT, path)) as fh:
        return json.load(fh)


def ablation_table() -> str:
    curves = {
        "raw (reference protocol)": "results/quantum_classical_comparison.json",
        "input-norm only": "results/ablation/norm_only/quantum_classical_comparison.json",
        "snr-jitter only": "results/ablation/jitter_only/quantum_classical_comparison.json",
        "norm + jitter (robust)": "results/robust/quantum_classical_comparison.json",
    }
    rows, snr = {}, None
    for label, path in curves.items():
        try:
            d = _load(path)
        except FileNotFoundError:
            rows[label] = None
            continue
        snr = d["snr"]
        rows[label] = d["acc"].get("quantum")
    if snr is None:
        raise SystemExit("no ablation curve files found — run scripts/r3_ablation.sh first")
    out = ["| Quantum-SC accuracy | " + " | ".join(f"{int(s)} dB" for s in snr) + " |"]
    out.append("|" + "---|" * (len(snr) + 1))
    for label, acc in rows.items():
        cells = (
            " | ".join(f"{a:.3f}" for a in acc) if acc else "(missing)"
        )
        out.append(f"| {label} | {cells} |")
    return "\n".join(out)


def multiseed_table() -> str:
    base = _load("results/robust/quantum_classical_comparison.json")
    snr = base["snr"]
    i5 = snr.index(5.0)
    per_seed = {"classical": [], "quantum": []}
    seeds = []
    for s in (1, 2, 3):
        try:
            d = _load(f"results/robust/seed{s}/quantum_classical_comparison.json")
        except FileNotFoundError:
            continue
        seeds.append(s)
        for k in per_seed:
            per_seed[k].append(d["acc"][k][i5])
    if not seeds:
        raise SystemExit("no per-seed eval files found — run scripts/r3_multiseed.sh first")
    lines = [
        "| Accuracy @ 5 dB | mean | spread (min..max) | per-seed |",
        "|---|---|---|---|",
    ]
    verdicts = {}
    for k, vals in per_seed.items():
        v = np.asarray(vals)
        verdicts[k] = v
        lines.append(
            f"| {'robust quantum SC' if k == 'quantum' else 'classical SC'} "
            f"| {v.mean():.3f} | {v.min():.3f}..{v.max():.3f} "
            f"| {', '.join(f'{x:.3f}' for x in v)} |"
        )
    beats = (
        "every seed" if np.all(verdicts["quantum"] > verdicts["classical"])
        else "NOT every seed"
    )
    lines.append(
        f"\nSeeds {seeds}, 30 epochs each (variance estimate; the headline "
        f"100-epoch single-seed curves are in the parent directory). The "
        f"robust quantum classifier beats the classical CNN at 5 dB in {beats}."
    )
    return "\n".join(lines)


if __name__ == "__main__":
    print(ablation_table())
    print()
    print(multiseed_table())
