#!/bin/bash
# Round-5 on-chip session: run the moment the tunnel is up, cheapest
# evidence first (windows between outages can be short):
#   1. full bench harness self-capture      -> results/bench_tpu_v5e_r5.json
#   2. perf decompositions (r4 asks, re-armed) -> results/perf_r5/
#   3. high-n backend microbench (ask #3)   -> results/perf_r5/high_n_microbench.json
#   4. full-protocol DCE control (ask #1d)  -> results/dce/ + runs/science
#   5. full-protocol seed-2 replicate (ask #5) -> results/dce/seed2/
# Each phase is independent and time-boxed; a dropped tunnel mid-way keeps
# earlier artifacts. Training phases are resume-capable, so re-running this
# script after an outage continues where it stopped. On re-fire, phases
# whose artifacts are already complete are SKIPPED, and a fast liveness
# probe runs between phases so a dropped tunnel exits the session in ~60 s
# (returning control to the watcher's probe loop) instead of hanging
# through every remaining phase timeout (~3.8 h, observed 08:35 window).
set -x
cd /root/repo
mkdir -p results/perf_r5 runs

probe_or_exit() {
  timeout 60 python -c \
    'import jax, jax.numpy as jnp; assert jax.default_backend()=="tpu"; jnp.ones((8,8)).sum().block_until_ready()' \
    || { echo "tunnel down at phase boundary — exiting for watcher re-fire"; exit 9; }
}

# Stop ALL CPU insurance trainers for the perf phases: on the 1-core host
# they contend with the session's host-side dispatch and would contaminate
# wall measurements (the r4 bench window's 2x contention, BENCH_r04 weak
# #1). Every trainer is resume-capable, so this loses nothing; [q]bracket
# avoids self-match.
pkill -f "[q]dml_tpu.cli train" 2>/dev/null
sleep 3

echo "=== phase 1: bench capture ==="
if [ -f results/bench_tpu_v5e_r5.json ]; then
  echo "phase 1 already captured — skipping"
else
# the harness emits the one-line record on stdout; keep the TPU record only
timeout 2000 python bench.py > /tmp/r5_bench_out.txt 2>/tmp/r5_bench_err.txt
tail -1 /tmp/r5_bench_out.txt > /tmp/r5_bench_line.json
python - <<'EOF'
import json
rec = json.load(open("/tmp/r5_bench_line.json"))
if str(rec.get("platform", "")).startswith("tpu"):
    with open("results/bench_tpu_v5e_r5.json", "w") as fh:
        json.dump(rec, fh, indent=1)
    print("bench captured:", rec["value"], rec.get("mfu"))
else:
    print("bench did NOT run on TPU:", rec.get("platform"), rec.get("tpu_error"))
EOF
fi

echo "=== phase 2: perf session ==="
if grep -q '"pallas_wins"' results/perf_r5/r5_perf_session.json 2>/dev/null; then
  echo "phase 2 already complete — skipping"
else
  probe_or_exit
  # the session resumes: probes already present in the out JSON are skipped
  QDML_PERF_OUT_DIR=results/perf_r5 timeout 2400 \
      python scripts/r4_perf_session.py results/perf_r5/r5_perf_session.json
fi

echo "=== phase 2.5: scan-variant A/B (headline-promotion evidence) ==="
if grep -q '"fast_wins"' results/perf_r5/scan_ab.json 2>/dev/null; then
  echo "phase 2.5 already complete — skipping"
else
  probe_or_exit
  timeout 1200 python scripts/r5_scan_ab.py results/perf_r5/scan_ab.json 5
fi

echo "=== phase 3: high-n microbench ==="
if grep -q fastest_fwdbwd_by_n results/perf_r5/high_n_microbench.json 2>/dev/null; then
  echo "phase 3 already complete — skipping"
else
  probe_or_exit
  timeout 1800 python scripts/r5_high_n_microbench.py \
      results/perf_r5/high_n_microbench.json
fi

echo "=== phase 4: science3 (full-protocol DCE control) ==="
# Provenance: the full-protocol reruns intentionally overwrite results/dce/
# and results/dce/seed2/ (the committed artifacts are REDUCED protocol —
# results/dce/PROTOCOL.md says this rerun supersedes them). Preserve the
# reduced-protocol curves once, under an explicit name, so the round-4
# study's evidence stays addressable after the overwrite (code review r5).
if [ ! -d results/dce/reduced30ep ]; then
  mkdir -p results/dce/reduced30ep results/dce/seed2/reduced30ep
  cp results/dce/*.jsonl results/dce/*.md results/dce/*.json results/dce/*.png \
      results/dce/reduced30ep/ 2>/dev/null
  cp results/dce/seed2/*.jsonl results/dce/seed2/*.md results/dce/seed2/*.json \
      results/dce/seed2/*.png results/dce/seed2/reduced30ep/ 2>/dev/null
fi
# stop any CPU-side insurance training still writing the EXACT workdir
# runs/science (two writers on one orbax workdir corrupt checkpoints);
# anchored so runs/science_cpu* / runs/science_s2 trainers are untouched
# (ADVICE r4); [b]racket avoids matching this script's own command line
pkill -f "[w]orkdir=runs/science( |$)" 2>/dev/null
sleep 3
probe_or_exit
timeout 5400 bash run_science3.sh && \
  echo "protocol: full reference (100 ep x 20k/cell), on-chip, $(date -u +%F)" \
      > results/dce/PROTOCOL_STAMP.txt

echo "=== phase 5: seed-2 full-protocol replicate ==="
pkill -f "[w]orkdir=runs/science_s2( |$)" 2>/dev/null
sleep 3
probe_or_exit
timeout 5400 bash scripts/r5_dce_seed2_full.sh

echo "R5 TPU SESSION DONE"
