#!/bin/bash
# Round-3 multi-seed variance estimate (VERDICT r2 #6): SC + robust-QSC at
# 3 seeds, 30 epochs, accuracy @ 5 dB with spread. Eval deliberately omits
# --data.seed so every seed scores on the COMMON seed-2026 fresh test
# stream: across-seed differences then measure training variance, not
# test-set resampling noise.
set -e
cd /root/repo
export JAX_PLATFORMS=cpu

for s in 1 2 3; do
  WD=runs/ms_s$s
  SEEDS="--train.seed=$s --data.seed=$((2026 + s))"
  python -m qdml_tpu.cli train-sc $SEEDS --train.n_epochs=30 \
      --train.workdir=$WD --train.resume=true > runs/ms_s$s.sc.log 2>&1
  python -m qdml_tpu.cli train-qsc --preset=robust_qsc $SEEDS --train.n_epochs=30 \
      --train.workdir=$WD --train.resume=true > runs/ms_s$s.qsc.log 2>&1
  mkdir -p $WD/Pn_128/robust_qsc
  for t in hdce_best hdce_best.meta.json; do
    cp -r runs/science/Pn_128/default/$t $WD/Pn_128/robust_qsc/ 2>/dev/null || true
  done
  # SC trained under "default" name; eval runs under the robust preset name —
  # bring its checkpoint over so one eval sees both classifiers.
  for t in sc_best sc_best.meta.json; do
    cp -r $WD/Pn_128/default/$t $WD/Pn_128/robust_qsc/ 2>/dev/null || true
  done
  python -m qdml_tpu.cli eval --preset=robust_qsc --train.seed=$s --train.workdir=$WD \
      --eval.results_dir=results/robust/seed$s > runs/ms_s$s.eval.log 2>&1
done
echo "MULTISEED DONE"
