#!/bin/bash
# Round-4 on-chip session: run the moment the tunnel is up, cheapest
# evidence first (windows between outages can be short):
#   1. full bench harness self-capture  -> results/bench_tpu_v5e_r4.json
#   2. perf decompositions (VERDICT r4 asks #1/#2) -> results/perf_r4/
#   3. the DCE control study (ask #3)   -> results/dce/ + runs/science
# Each phase is independent; a dropped tunnel mid-way keeps earlier
# artifacts. Training phases are resume-capable, so re-running this script
# after an outage continues where it stopped.
set -x
cd /root/repo
mkdir -p results/perf_r4 runs

echo "=== phase 1: bench capture ==="
# the harness emits the one-line record on stdout; keep the TPU record only
python bench.py > /tmp/r4_bench_out.txt 2>/tmp/r4_bench_err.txt
tail -1 /tmp/r4_bench_out.txt > /tmp/r4_bench_line.json
python - <<'EOF'
import json, shutil
rec = json.load(open("/tmp/r4_bench_line.json"))
if str(rec.get("platform", "")).startswith("tpu"):
    with open("results/bench_tpu_v5e_r4.json", "w") as fh:
        json.dump(rec, fh, indent=1)
    print("bench captured:", rec["value"], rec.get("mfu"))
else:
    print("bench did NOT run on TPU:", rec.get("platform"), rec.get("tpu_error"))
EOF

echo "=== phase 2: perf session ==="
timeout 2400 python scripts/r4_perf_session.py results/perf_r4/r4_perf_session.json

echo "=== phase 3: science3 (DCE control) ==="
# stop any CPU-side insurance training still writing the EXACT workdir
# runs/science (two writers on one orbax workdir corrupt checkpoints);
# anchored so runs/science_cpu* seed-study trainers are untouched (ADVICE
# r4); [b]racket avoids matching this script's own command line
pkill -f "[w]orkdir=runs/science( |$)" 2>/dev/null
sleep 3
timeout 5400 bash run_science3.sh

echo "R4 TPU SESSION DONE"
