"""Angle-saturation analysis for the raw-pilot low-SNR collapse (VERDICT r2
missing #3 / next #5).

Loads the reference-protocol (raw-pilot) QSC checkpoint and measures, per
eval SNR: the pilot-image RMS, the pre-tanh Dense activations, the fraction
of saturated angles (|tanh| > 0.99), and the classifier accuracy — with and
without per-sample RMS input normalization on the SAME params. If the
collapse is input-scale-driven tanh saturation, the raw path should show
power growing as SNR drops with angles saturating, while the normalized
path holds the trained activation range at every SNR.

Usage: JAX_PLATFORMS=cpu python scripts/r3_angle_analysis.py [workdir] [out.json]
"""

import json
import sys

from qdml_tpu.utils.platform import honor_platform_env

honor_platform_env()

import jax.numpy as jnp
import numpy as np

from qdml_tpu.config import ExperimentConfig
from qdml_tpu.data.channels import ChannelGeometry
from qdml_tpu.data.datasets import make_network_batch
from qdml_tpu.models.qsc import QSCP128
from qdml_tpu.train.checkpoint import reconcile_quantum_cfg, restore_checkpoint

workdir = sys.argv[1] if len(sys.argv) > 1 else "runs/science/Pn_128/default"
out_path = sys.argv[2] if len(sys.argv) > 2 else "results/ablation/angle_saturation.json"

cfg = ExperimentConfig()
qsc_vars, meta = restore_checkpoint(workdir, "qsc_best")
cfg = reconcile_quantum_cfg(cfg, meta)
geom = ChannelGeometry.from_config(cfg.data)

BS = 1024
rows = []
for snr in (5.0, 10.0, 15.0):
    i = jnp.arange(BS)
    scen = i % cfg.data.n_scenarios
    user = (i // cfg.data.n_scenarios) % cfg.data.n_users
    batch = make_network_batch(
        jnp.uint32(cfg.data.seed), scen, user, cfg.data.data_len * 3 + i,
        jnp.float32(snr), geom,
    )
    x = batch["yp_img"]
    for norm in (False, True):
        model = QSCP128(
            n_qubits=cfg.quantum.n_qubits,
            n_layers=cfg.quantum.n_layers,
            n_classes=cfg.quantum.n_classes,
            backend="dense",
            input_norm=norm,
        )
        logp, inter = model.apply(
            qsc_vars, x, train=False, capture_intermediates=True
        )
        tree = inter["intermediates"]["QSCPreprocess_0"]["Dense_0"]["__call__"][0]
        pre = np.asarray(tree)
        angles = np.tanh(pre)
        acc = float(jnp.mean(jnp.argmax(logp, -1) == batch["indicator"]))
        rows.append(
            {
                "snr_db": snr,
                "input_norm": norm,
                "pilot_rms": float(jnp.sqrt(jnp.mean(x**2))),
                "pre_tanh_abs_mean": float(np.abs(pre).mean()),
                "pre_tanh_abs_p95": float(np.quantile(np.abs(pre), 0.95)),
                "saturated_frac": float((np.abs(angles) > 0.99).mean()),
                "accuracy": acc,
            }
        )
        print(rows[-1], flush=True)

with open(out_path, "w") as fh:
    json.dump(rows, fh, indent=1)
print("wrote", out_path)
