#!/usr/bin/env bash
# graftlint gate — the exact invocation CI (scripts/run_tier1.sh) runs.
# Exit 0: every finding fixed, suppressed inline with a reason, or
# grandfathered in scripts/lint_baseline.json. Exit 1: new findings.
# Pass extra flags through, e.g.:
#   scripts/run_lint.sh --durations=/tmp/durations.log   # + slow-marker rule
#   scripts/run_lint.sh --json=/tmp/lint.json            # machine-readable gate
cd "$(dirname "$0")/.." || exit 2
exec python -m qdml_tpu.cli lint --baseline "$@"
