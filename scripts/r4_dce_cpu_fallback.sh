#!/bin/bash
# REDUCED-protocol CPU fallback for the monolithic-DCE control study
# (VERDICT r3 ask #3). The full reference protocol (100 epochs, 20k
# samples/cell) is a minutes-scale job on the TPU but ~20 hours on this
# 1-core host, so if the tunnel stays down for the whole round this
# trains the control at 30 epochs x 4k samples/cell — enough to measure
# the architectural ordering (hierarchical HDCE vs monolithic DCE vs
# LS/MMSE) with every estimator under ONE consistent protocol, clearly
# labelled as reduced. run_science3.sh (TPU, full protocol) writes the
# same results/dce/ and supersedes this when it runs.
set -e
cd /root/repo
WD=runs/science_cpu
RED="--data.data_len=4000 --train.n_epochs=30"
# NO scan_steps here: in-scan synthesis regenerates every batch on device
# each step — the right trade on the TPU (it removes the dispatch gap,
# docs/ROOFLINE.md) but pure overhead on CPU, where the loader path
# generates the epoch data once and re-serves it (~4x faster end to end).
for cmd in train-hdce train-sc train-qsc train-dce; do
  echo "=== $cmd (REDUCED protocol: 30 epochs, 4k/cell) ==="
  python -m qdml_tpu.cli $cmd $RED --train.workdir=$WD --train.resume=true
done
python -m qdml_tpu.cli eval --data.data_len=4000 --train.workdir=$WD \
    --eval.results_dir=results/dce
# commit-durable copy of the per-SNR eval rows (run dirs are gitignored)
cp $WD/Pn_128/*/eval.metrics.jsonl results/dce/ 2>/dev/null || true
cat > results/dce/PROTOCOL.md <<'EOF'
# Protocol note

These curves were produced by `scripts/r4_dce_cpu_fallback.sh` under a
REDUCED training protocol — 30 epochs, 4,000 samples per (scenario, user)
cell — on the CPU backend, because the TPU tunnel was down for the whole
round-4 window (see BENCH_r04.json probe_attempts). The reference
protocol is 100 epochs x 20,000 samples/cell (`Runner...py:20-38`);
`run_science3.sh` trains exactly that on-chip in minutes and overwrites
this directory when the tunnel allows. All four estimators here
(LS / MMSE / monolithic DCE / hierarchical HDCE) share the one reduced
protocol, so the architectural ORDERING is internally consistent even
though absolute NMSE is a few dB short of the full-protocol curves.
EOF
echo "DCE CPU FALLBACK DONE"
