"""On-chip micro-benchmark: quantum-circuit forward formulations + QSC steps.

Run on the real TPU when the tunnel is up:
    python scripts/r3_quantum_microbench.py [out.json]

Measures, at the shipped shape (n=6, L=3, batch 2304):
  - forward-only: dense (closed-form product state), pallas (whole-circuit
    kernel), pallas_old (round-2 psi-input kernel), tensor
  - full QSC train step: dense vs pallas backends
  - HDCE train step f32/bf16 (donation now on) for the MFU item
"""

import json
import sys
import time

from qdml_tpu.utils.compile_cache import enable_compile_cache

enable_compile_cache()

import jax
import jax.numpy as jnp
import numpy as np

BATCH = 2304
N, L = 6, 3


def timed(fn, *args, reps=50):
    out = fn(*args)
    jnp.asarray(out).block_until_ready()
    float(jnp.sum(out))  # host transfer forces execution through the tunnel
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    float(jnp.sum(out))
    return (time.perf_counter() - t0) / reps


def _bench_hdce_bs(bench, cell_bs: int) -> dict:
    """bench._bench_hdce at a non-reference cell batch (same FLOP model)."""
    saved = bench._CELL_BS
    bench._CELL_BS = cell_bs
    try:
        out = bench._bench_hdce("bfloat16", 50, 60.0)
    finally:
        bench._CELL_BS = saved
    return out


def capture_trace(out_dir: str = "runs/r3_tpu_trace"):
    """jax.profiler trace of EXACTLY the bench's bf16 HDCE step setup —
    shared builders, same _CELL_BS — so the trace explains the same shape
    the benchmark measured."""
    from qdml_tpu.config import DataConfig, ExperimentConfig, ModelConfig, TrainConfig
    from qdml_tpu.train.hdce import init_hdce_state, make_hdce_train_step

    sys.path.insert(0, ".")
    import bench

    cfg = ExperimentConfig(
        data=DataConfig(),
        model=ModelConfig(dtype="bfloat16"),
        train=TrainConfig(batch_size=bench._CELL_BS, n_epochs=1),
    )
    batch = bench._make_grid_batch(cfg)
    batch = {k: batch[k] for k in ("yp_img", "h_label", "h_perf")}
    model, state = init_hdce_state(cfg, steps_per_epoch=100)
    step = make_hdce_train_step(model, state.tx)
    state, m = step(state, batch)
    float(m["loss"])
    with jax.profiler.trace(out_dir):
        for _ in range(10):
            state, m = step(state, batch)
        float(m["loss"])
    print("trace ->", out_dir, flush=True)


def main():
    print("backend:", jax.default_backend(), flush=True)
    rng = np.random.default_rng(0)
    angles = jnp.asarray(rng.uniform(-1, 1, (BATCH, N)).astype(np.float32))
    w = jnp.asarray(rng.uniform(-3, 3, (L, N, 2)).astype(np.float32))

    from qdml_tpu.quantum.circuits import angle_embed, ansatz_unitary, run_circuit
    from qdml_tpu.quantum import statevector as sv
    from qdml_tpu.quantum.pallas_kernels import fused_unitary_expvals

    res = {"backend": jax.default_backend(), "batch": BATCH, "n": N, "layers": L}

    for backend in ("dense", "pallas", "tensor"):
        f = jax.jit(lambda a, ww, b=backend: run_circuit(a, ww, N, L, b))
        dt = timed(f, angles, w)
        res[f"fwd_{backend}_us"] = round(dt * 1e6, 1)
        res[f"fwd_{backend}_sps"] = round(BATCH / dt, 1)

    # round-2 psi-input kernel as the baseline comparison
    def old_pallas(a, ww):
        psi = angle_embed(sv.zero_state(N, (a.shape[0],)), a, N)
        return fused_unitary_expvals(psi, ansatz_unitary(ww, N, L), N)

    dt = timed(jax.jit(old_pallas), angles, w)
    res["fwd_pallas_old_us"] = round(dt * 1e6, 1)
    res["fwd_pallas_old_sps"] = round(BATCH / dt, 1)

    # full train steps via the bench harness's own builders
    sys.path.insert(0, ".")
    import bench

    for key, fn in (
        ("qsc_dense", lambda: bench._bench_qsc("dense", 50, 45.0)),
        ("qsc_pallas", lambda: bench._bench_qsc("pallas", 50, 45.0)),
        ("hdce_f32", lambda: bench._bench_hdce("float32", 50, 60.0)),
        ("hdce_bf16", lambda: bench._bench_hdce("bfloat16", 50, 60.0)),
        # batch-scaling probe for the MFU item: if MFU rises materially at
        # 512/cell the 256-step carries fixed overhead; if flat, it is
        # bandwidth-bound at this model size (roofline evidence either way)
        ("hdce_bf16_b512", lambda: _bench_hdce_bs(bench, 512)),
        ("hdce_bf16_b1024", lambda: _bench_hdce_bs(bench, 1024)),
    ):
        try:
            res[key] = fn()
        except Exception as e:  # noqa: BLE001
            res[key] = {"error": f"{type(e).__name__}: {e}"}
        print(key, res[key], flush=True)

    out_path = sys.argv[1] if len(sys.argv) > 1 else "runs/r3_quantum_microbench.json"
    with open(out_path, "w") as fh:
        json.dump(res, fh, indent=1)
    print(json.dumps(res))
    try:
        capture_trace()
    except Exception as e:  # noqa: BLE001
        print("trace capture failed:", e, flush=True)


if __name__ == "__main__":
    main()
