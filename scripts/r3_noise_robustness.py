"""Hardware-noise robustness study: does QuantumNAT training help under
STATE-level noise?

QuantumNAT (arXiv:2110.11331; reference ``Estimators...py:176-199``) injects
parameter noise during training to prepare the classifier for noisy quantum
hardware. The reference can never test that premise — its PennyLane
``default.qubit`` is noiseless. This framework's trajectory simulator
(:mod:`qdml_tpu.quantum.trajectories`) can: evaluate two trained QSCs (one
QuantumNAT-trained, one plain) under depolarizing noise of increasing
strength and compare accuracy degradation.

Usage (after scripts/r3_noise_robustness.sh trains the two checkpoints):
    python scripts/r3_noise_robustness.py [plain_workdir nat_workdir out_dir]
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from qdml_tpu.utils.platform import honor_platform_env

honor_platform_env()

import jax
import jax.numpy as jnp

from qdml_tpu.config import ExperimentConfig
from qdml_tpu.data.channels import ChannelGeometry
from qdml_tpu.data.datasets import make_network_batch
from qdml_tpu.models.qsc import QSCP128
from qdml_tpu.train.checkpoint import reconcile_quantum_cfg, restore_checkpoint

P_GRID = (0.0, 0.01, 0.03, 0.1, 0.2)
N_TRAJ = 32
TEST_N = 4608  # 2 full grid batches of fresh samples
SNRS = (5.0, 10.0)


def common_test_batches(cfg: ExperimentConfig, geom: ChannelGeometry) -> dict:
    """The studies' COMMON fresh test stream: one batch per SNR, offset past
    the training data (``Test.py:127`` start-offset convention), keyed by the
    shared ``cfg.data.seed`` so every noise study scores the same samples."""
    start = cfg.data.data_len * 3
    i = jnp.arange(start, start + TEST_N)
    return {
        snr: make_network_batch(
            jnp.uint32(cfg.data.seed), i % 3, (i // 3) % 3, i, jnp.float32(snr), geom
        )
        for snr in SNRS
    }


def accuracy(model: QSCP128, vars_: dict, batch, key) -> float:
    rngs = {"trajectories": key} if model.depolarizing_p > 0 else None
    logp = model.apply(vars_, batch["yp_img"], train=False, rngs=rngs)
    pred = jnp.argmax(logp, -1)
    return float(jnp.mean((pred == batch["indicator"]).astype(jnp.float32)))


def write_results(out_dir: str, out: dict, row_header: str) -> str:
    """results.json + markdown accuracy-vs-p table, shared by the noise
    studies so the artifact format cannot drift between them. The table's
    p columns come from ``out["p_grid"]`` — the same grid the JSON records —
    so the two artifacts cannot disagree."""
    p_grid = out["p_grid"]
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "results.json"), "w") as fh:
        json.dump(out, fh, indent=1)
    lines = [
        f"| {row_header} | " + " | ".join(f"p={p:g}" for p in p_grid) + " |",
        "|---|" + "---|" * len(p_grid),
    ]
    for k, accs in out["curves"].items():
        lines.append(f"| {k} | " + " | ".join(f"{a:.3f}" for a in accs) + " |")
    with open(os.path.join(out_dir, "results_table.md"), "w") as fh:
        fh.write("\n".join(lines) + "\n")
    return "\n".join(lines)


def main() -> None:
    plain_wd = sys.argv[1] if len(sys.argv) > 1 else "runs/nr_plain/Pn_128/default"
    nat_wd = sys.argv[2] if len(sys.argv) > 2 else "runs/nr_nat/Pn_128/default"
    out_dir = sys.argv[3] if len(sys.argv) > 3 else "results/noise_robustness"
    labels = sys.argv[4:6] if len(sys.argv) > 5 else ["plain", "quantumnat"]

    cfg = ExperimentConfig()
    geom = ChannelGeometry.from_config(cfg.data)
    batches = common_test_batches(cfg, geom)

    out = {"p_grid": list(P_GRID), "n_trajectories": N_TRAJ, "test_n": TEST_N, "curves": {}}
    for label, wd in ((labels[0], plain_wd), (labels[1], nat_wd)):
        vars_, meta = restore_checkpoint(wd, "qsc_best")
        # standard architecture reconciliation (input_norm has no params, so
        # a mismatch would silently change the preprocess)
        mcfg = reconcile_quantum_cfg(cfg, meta)
        for snr in SNRS:
            accs = []
            for p in P_GRID:
                model = QSCP128(
                    n_qubits=mcfg.quantum.n_qubits,
                    n_layers=mcfg.quantum.n_layers,
                    n_classes=mcfg.quantum.n_classes,
                    input_norm=mcfg.quantum.input_norm,
                    backend="tensor",
                    depolarizing_p=float(p),
                    n_trajectories=N_TRAJ,
                )
                accs.append(
                    round(accuracy(model, vars_, batches[snr], jax.random.PRNGKey(17)), 4)
                )
            out["curves"][f"{label}_snr{snr:g}"] = accs
            print(f"{label} @ SNR {snr:g}: {accs}", flush=True)

    print(write_results(out_dir, out, "model / SNR"))


if __name__ == "__main__":
    main()
