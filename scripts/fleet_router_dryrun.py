"""Fleet-router dryrun over REAL backend serve processes (ISSUE 14).

The multi-process proof of the router tier (docs/FLEET.md): spawn >= 2
genuine ``qdml-tpu serve`` processes (own interpreters, own JAX runtimes,
own warmups, own compile counters — fleet/spawn.py reads each one's
post-bind banner), front them with a :class:`FleetRouter` speaking the
serve protocol on its own socket, drive MMPP loadgen traffic THROUGH the
router, and chaos-test the tier with the seeded :class:`FaultPlan`
schedule — backend kill mid-traffic (SIGKILL), backend stall (SIGSTOP),
router-side socket garbage — plus a fan-out ``{"op": "swap"}`` under live
traffic and a FleetController adaptation pass over the router's aggregated
verbs. Per the repo's dryrun noise discipline, BEHAVIOR gates are
absolute/invariant and latency %-rows are judged only against interleaved
contemporaneous windows:

- **zero stranded futures** in every window (always-armed report gate);
- **zero request-path compiles on every surviving backend** (each process's
  own post-warmup counter delta, polled directly at the end);
- **fleet-wide dedup**: a same-id retry — including one whose original
  backend has been KILLED — lands exactly one dispatch fleet-wide;
- **fan-out swap**: both backends reach swap epoch 1 under traffic;
- **ejection/re-admission**: the killed/stalled backend ejects (typed
  failovers, surviving host keeps serving) and re-admits after respawn/
  resume;
- **controller over the router**: drift detected on aggregated stats ->
  single-trunk fine-tune -> canary -> TAGGED swap fanned to all backends ->
  watch window confirms; with one backend ejected the NEXT episode still
  adapts the survivors (partial fan-out reported, never suspended);
- **report round-trip exit 0** per fault class (recovery best-of vs
  interleaved contemporaneous baseline best-of, 50%% threshold on this
  2-core harness) with the fleet-router line naming the topology.

Writes ``results/fleet_router/``: ``baseline[_tN].jsonl``,
``{class}_fault.jsonl``, ``{class}_recovery_tN.jsonl`` /
``{class}_base_tN.jsonl``, ``report_{class}.md``, ``FLEET_ROUTER.json``.

Run: ``python scripts/fleet_router_dryrun.py [--n=240] [--rate=300]
[--deadline-ms=500] [--seed=0]``
"""

from __future__ import annotations

import json
import os
import socket
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from qdml_tpu.utils.platform import force_cpu  # noqa: E402


def _arg(argv, name, default):
    return next((a.split("=", 1)[1] for a in argv if a.startswith(f"--{name}=")), default)


def _free_port() -> int:
    with socket.socket() as sk:
        sk.bind(("127.0.0.1", 0))
        return sk.getsockname()[1]


def main(argv: list[str]) -> int:
    n = int(_arg(argv, "n", "240"))
    rate = float(_arg(argv, "rate", "300"))
    deadline_ms = float(_arg(argv, "deadline-ms", "500"))
    threshold = _arg(argv, "threshold", "50")  # %-rows: identical code, 2-core tail noise
    seed = int(_arg(argv, "seed", "0"))
    trials = int(_arg(argv, "trials", "3"))
    force_cpu(2)

    import asyncio
    from concurrent.futures import Future

    from qdml_tpu.config import (
        ControlConfig,
        DataConfig,
        ExperimentConfig,
        ModelConfig,
        ServeConfig,
        TrainConfig,
    )
    from qdml_tpu.control.loop import FleetController
    from qdml_tpu.fleet import FleetPoller, FleetRouter, route_async, spawn_backend
    from qdml_tpu.serve import (
        FaultPlan,
        FaultSpec,
        ServeClient,
        make_request_samples,
        run_loadgen_socket,
    )
    from qdml_tpu.telemetry import run_manifest
    from qdml_tpu.telemetry.report import report_main
    from qdml_tpu.train.hdce import train_hdce
    from qdml_tpu.train.qsc import train_classifier
    from qdml_tpu.utils.metrics import MetricsLogger

    out_dir = os.path.join("results", "fleet_router")
    os.makedirs(out_dir, exist_ok=True)
    scratch = tempfile.mkdtemp(prefix="fleet_")

    cfg = ExperimentConfig(
        name="fleet_router_dryrun",
        data=DataConfig(n_ant=16, n_sub=8, n_beam=4, data_len=64),
        model=ModelConfig(features=8),
        train=TrainConfig(batch_size=16, n_epochs=8, workdir=scratch, probe_every=0),
        serve=ServeConfig(
            max_batch=16, buckets=(4, 16), max_wait_ms=2.0, max_queue=64,
            batching="bucket",  # two processes racing one auto table is the
            # autotune_corrupt chaos class's job, not this dryrun's
            dedup_ttl_s=10.0, conn_timeout_s=5.0, supervise=True,
        ),
        control=ControlConfig(
            min_window=6, ft_steps=300, ft_batch=16, probe_n=32,
            watch_ticks=2, autoscale=False,
            # gain gate scaled to this harness: an 8-epoch tiny model's
            # absolute dB headroom is small (the trained-scale dryrun,
            # scripts/control_dryrun.py, keeps the 0.3 default and clears
            # it by 1.3 dB) — the GATE semantics (candidate must beat live
            # on drifted probes, zero frozen-family regression) are intact
            min_gain_db=0.2,
        ),
    )
    # TRAIN the fleet's models briefly (control_dryrun's pattern): the
    # controller phase's canary compares candidate vs live on real drifted
    # probes, and gains over an UNTRAINED init are sub-noise — a trained
    # model degrades under drift and recovers under fine-tune, which is the
    # signal the gate measures. Checkpoints land where the backends' CLI
    # workdir resolution will look (hdce/sc, best + last tags).
    import dataclasses

    workdir = os.path.join(scratch, f"Pn_{cfg.data.pilot_num}", cfg.name)
    print("training fleet models (8-epoch HDCE + 8-epoch SC) ...", flush=True)
    tlog = MetricsLogger(os.path.join(scratch, "train.jsonl"), echo=False,
                         manifest=run_manifest(cfg))
    try:
        train_hdce(cfg, logger=tlog, workdir=workdir)
        sc_cfg = dataclasses.replace(
            cfg, train=dataclasses.replace(cfg.train, n_epochs=8)
        )
        train_classifier(sc_cfg, quantum=False, logger=tlog, workdir=workdir)
    finally:
        tlog.close()
    samples = make_request_samples(cfg, n)

    backend_overrides = [
        "--name=fleet_router_dryrun",
        "--data.n_ant=16", "--data.n_sub=8", "--data.n_beam=4",
        "--data.data_len=64", "--model.features=8", "--train.batch_size=16",
        f"--train.workdir={scratch}",
        "--serve.max_batch=16", "--serve.buckets=(4,16)",
        "--serve.max_wait_ms=2.0", "--serve.max_queue=64",
        "--serve.batching=bucket", "--serve.dedup_ttl_s=10.0",
        "--serve.conn_timeout_s=5.0", "--serve.supervise=true",
    ]
    ports = [_free_port(), _free_port()]  # FIXED ports: a respawned backend
    # reuses its address, so the router re-admits the same table entry

    def spawn(i: int):
        print(f"spawning backend {i} on :{ports[i]} ...", flush=True)
        b = spawn_backend(backend_overrides, port=ports[i])
        print(json.dumps({"backend": i, "port": b.port, "host_id": b.host_id,
                          "compiles_after_warmup": b.banner[
                              "compile_cache_after_warmup"]}), flush=True)
        return b

    backends = [spawn(0), spawn(1)]
    router = FleetRouter(
        [("127.0.0.1", p) for p in ports],
        balance="hash", timeout_s=2.0, retries=0,
        eject_failures=2, eject_s=0.5, readmit_probes=1,
        poll_interval_s=0.2, failover=2, seed=seed,
        # the kill-spanning dedup pin retries its id AFTER a full fault
        # window + drain on a contended host: the TTL must outlive that
        dedup_ttl_s=300.0,
    ).start()
    aloop = asyncio.new_event_loop()
    tloop = threading.Thread(target=aloop.run_forever, daemon=True)
    tloop.start()
    ready: Future = Future()
    front_task = asyncio.run_coroutine_threadsafe(
        route_async(router, "127.0.0.1", 0, ready,
                    conn_timeout_s=5.0, max_line_bytes=1 << 20),
        aloop,
    )
    front = ("127.0.0.1", ready.result(timeout=30.0))
    print(json.dumps({"router_front": front[1], "balance": router.balance}), flush=True)

    window_seq = [0]

    def serve_window(tag: str, during=None):
        side_err: list = []
        side = None
        if during is not None:
            def _side():
                try:
                    during()
                except Exception as e:  # lint: disable=broad-except(the injection side thread must report its failure into the headline, not die silently and fake a passing chaos run)
                    side_err.append(f"{type(e).__name__}: {e}")
            side = threading.Thread(target=_side, daemon=True)
            side.start()
        path = os.path.join(out_dir, f"{tag}.jsonl")
        logger = MetricsLogger(path, echo=False, manifest=run_manifest(cfg))
        # one seed per WINDOW: loadgen ids are lg{seed}-{i}, and a reused id
        # would re-attach to the router's fleet-wide dedup window from an
        # EARLIER trial — every window after the first would measure cache
        # hits, not serving (caught by a backend completed-counter audit)
        window_seq[0] += 1
        try:
            summary = run_loadgen_socket(
                cfg, front, rate=rate, n=n, seed=seed + 1000 * window_seq[0],
                deadline_ms=deadline_ms, logger=logger, clients=8,
                x=samples["x"],
            )
        finally:
            logger.close()
        if side is not None:
            side.join(timeout=60.0)
        if side_err:
            summary["injection_error"] = side_err[0]
        return summary, path

    def _p99(s):
        return ((s["latency_ms"] or {}).get("p99_ms")) or float("inf")

    def backend_poll(port: int, verb: str = "metrics") -> dict | None:
        """Direct per-backend poll (NOT through the router): each process's
        own compile gate and swap epoch, attributable."""
        try:
            with ServeClient("127.0.0.1", port, timeout_s=5.0, retries=1) as c:
                rep = c.metrics() if verb == "metrics" else c.health()
                return rep.get(verb)
        except Exception:  # lint: disable=broad-except(a dead backend is an expected poll outcome mid-chaos; the caller records None)
            return None

    def per_port_completed() -> dict:
        """Each live backend's own completed counter (the fleet-wide
        dispatch ledger the dedup pins compare; a dead backend reads None)."""
        out = {}
        for p in ports:
            m = backend_poll(p)
            out[p] = None if m is None else int(m.get("completed") or 0)
        return out

    def _rid_for_primary(port: int) -> str:
        """A request id whose consistent-hash primary is the given backend
        (the kill-spanning pin must target the victim's id space)."""
        k = 0
        while True:
            rid = f"pin-{seed}-{k}"
            if router._candidates(rid)[0].port == port:
                return rid
            k += 1

    def dedup_retry_pin(rid: str, rep1: dict) -> dict:
        """QUIET-phase fleet-wide dedup pin: retry an already-served id —
        identical reply, a router dedup hit, and ZERO new dispatches on any
        live backend (per-port counters bitwise unchanged; runs with no
        concurrent traffic so the ledger comparison is exact)."""
        before = per_port_completed()
        hits0 = router.dedup.hits
        with ServeClient(front[0], front[1], timeout_s=10.0, retries=1,
                         backoff_s=0.05, seed=seed) as client:
            rep2 = client.request(samples["x"][0], rid=rid)
        after = per_port_completed()
        ok = (
            rep1.get("ok") is True and rep2.get("ok") is True
            and rep1.get("h") == rep2.get("h")
            and rep2.get("pred") == rep1.get("pred")
            and router.dedup.hits == hits0 + 1
            and all(after[p] == before[p] for p in ports
                    if before[p] is not None and after[p] is not None)
        )
        return {"ok": ok, "rid": rid, "dedup_hits": router.dedup.hits,
                "completed_before": before, "completed_after": after}

    headline: dict = {
        "n": n, "rate": rate, "deadline_ms": deadline_ms, "seed": seed,
        "report_threshold_pct": float(threshold),
        "note": (
            "2-process wiring proof on the 2-core harness: behavior gates "
            "(stranded futures, per-backend compile deltas, dedup, swap "
            "epochs, ejection/readmission, SLO re-attainment within 0.05 "
            "absolute) are absolute/invariant; %-threshold latency rows "
            "compare identical code across interleaved contemporaneous "
            "windows at 50% (real hardware re-runs arm the default 10%)"
        ),
        "backends": {b.host_id: {"port": b.port} for b in backends},
        "classes": {},
    }
    all_pass = True

    def finish_class(kind: str, checks: dict, ok: bool) -> None:
        nonlocal all_pass
        checks["ok"] = ok
        headline["classes"][kind] = checks
        all_pass = all_pass and ok
        print(json.dumps({kind: {"ok": ok}}), flush=True)

    # ---------------- baseline: healthy fleet, best-of-N ---------------------
    base_summary = base_path = None
    for trial in range(trials):
        s, p = serve_window(f"baseline_t{trial}" if trial else "baseline")
        if base_summary is None or _p99(s) < _p99(base_summary):
            base_summary, base_path = s, p
    both_served = all(
        (v or {}).get("completed") for v in
        (base_summary.get("server_metrics") or {}).get("per_backend", {}).values()
    ) and len((base_summary.get("server_metrics") or {}).get("per_backend", {})) == 2
    # serving audit: the backends' own counters must account for (nearly)
    # every offered request across all three windows — a router answering
    # from its dedup cache (reused ids) would leave them flat and silently
    # turn every latency row into a cache-hit measurement
    served_total = sum(v or 0 for v in per_port_completed().values())
    finish_class("baseline", {
        "completed": base_summary["completed"],
        "stranded_futures": base_summary["stranded_futures"],
        "slo": base_summary["slo"],
        "router": base_summary.get("router"),
        "both_backends_served": both_served,
        "backend_completed_total": served_total,
        "offered_total": trials * n,
        "path": base_path,
    }, (
        base_summary["stranded_futures"] == 0 and both_served
        and served_total >= trials * n - n // 10
    ))

    # ---------------- fan-out swap under live traffic ------------------------
    swap_box: dict = {}

    def inject_swap():
        time.sleep((n // 3) / rate)  # mid-window
        with ServeClient(front[0], front[1], timeout_s=60.0) as c:
            swap_box["reply"] = c.swap(tags={"hdce": "hdce_last", "sc": "sc_last"})

    s, _p = serve_window("swap_fault", during=inject_swap)
    epochs = {p: ((backend_poll(p, "health") or {}).get("swap_epoch")) for p in ports}
    rep = swap_box.get("reply") or {}
    finish_class("fanout_swap", {
        "stranded_futures": s["stranded_futures"],
        "swap_reply_ok": rep.get("ok"),
        "fanned_to": (rep.get("swap") or {}).get("fanned_to"),
        "backend_swap_epochs": epochs,
        "injection_error": s.get("injection_error"),
    }, (
        s["stranded_futures"] == 0 and rep.get("ok") is True
        and (rep.get("swap") or {}).get("fanned_to") == 2
        and all(e == 1 for e in epochs.values())
        and s.get("injection_error") is None
    ))

    # ---------------- router-side socket garbage -----------------------------
    def inject_garbage():
        time.sleep((n // 4) / rate)
        with socket.create_connection(front, timeout=10.0) as sk:
            sk.settimeout(10.0)
            fh = sk.makefile("rb")
            sk.sendall(b"NOT JSON {{{\n")
            assert json.loads(fh.readline()) == {"ok": False, "reason": "bad_json"}, "garbage"
        sk2 = socket.create_connection(front, timeout=10.0)
        sk2.sendall(b'{"id": "frag", "x": [[')  # partial line, then vanish
        sk2.close()
        with socket.create_connection(front, timeout=10.0) as sk3:
            sk3.settimeout(10.0)
            fh = sk3.makefile("rb")
            sk3.sendall(b'{"id": 1, "x": "' + b"a" * (1 << 21) + b'"}\n')
            rep_ = json.loads(fh.readline())
            assert rep_["ok"] is False and "max_line_bytes" in rep_["reason"], rep_

    s, _p = serve_window("router_garbage_fault", during=inject_garbage)
    finish_class("router_garbage", {
        "stranded_futures": s["stranded_futures"],
        "give_ups": s["give_ups"],
        "injection_error": s.get("injection_error"),
        "slo": s["slo"],
    }, s["stranded_futures"] == 0 and s.get("injection_error") is None)

    # quiet-phase fleet-wide dedup pin (healthy fleet)
    with ServeClient(front[0], front[1], timeout_s=10.0, retries=1,
                     seed=seed) as _c:
        _rep1 = _c.request(samples["x"][0], rid=f"pin-quiet-{seed}")
    pin_quiet = dedup_retry_pin(f"pin-quiet-{seed}", _rep1)
    finish_class("dedup_retry", pin_quiet, pin_quiet["ok"])

    # ---------------- chaos classes: kill + stall ----------------------------
    def run_chaos(kind: str, inject, recover) -> None:
        rsum0 = router.router_summary()  # class checks read DELTAS, not
        # the cumulative fleet-lifetime counters
        s_fault, _pf = serve_window(f"{kind}_fault", during=inject)
        recover()
        # router re-admits the recovered/respawned backend before measuring
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and len(router.live_backends()) < 2:
            router.poll_once()
            time.sleep(0.1)
        rec_summary = rec_path = lb_summary = lb_path = None
        rec_trials = []
        for trial in range(trials):
            s, p = serve_window(f"{kind}_recovery_t{trial}")
            rec_trials.append({
                "trial": trial,
                "stranded_futures": s["stranded_futures"],
                "give_ups": s["give_ups"],
                "hard_give_ups": s["give_ups"] - s["deadline_give_ups"],
                "p99_ms": (s["latency_ms"] or {}).get("p99_ms"),
                "slo": s["slo"],
            })
            if rec_summary is None or _p99(s) < _p99(rec_summary):
                rec_summary, rec_path = s, p
            sb, pb = serve_window(f"{kind}_base_t{trial}")
            if lb_summary is None or _p99(sb) < _p99(lb_summary):
                lb_summary, lb_path = sb, pb
        report_md = os.path.join(out_dir, f"report_{kind}.md")
        rc = report_main(
            [f"--current={rec_path}", f"--baseline={lb_path}",
             f"--threshold={threshold}", f"--out={report_md}"]
        )
        with open(report_md) as fh:
            fleet_line = next((ln.strip() for ln in fh if "via router over" in ln), None)
        rsum = router.router_summary()
        rec_att = (rec_summary["slo"] or {}).get("attainment")
        lb_att = (lb_summary["slo"] or {}).get("attainment")
        slo_ok = rec_att is not None and (lb_att is None or rec_att >= lb_att - 0.05)
        checks = {
            "stranded_futures_fault": s_fault["stranded_futures"],
            "stranded_futures_recovery": max(t["stranded_futures"] for t in rec_trials),
            "hard_give_ups_recovery": max(t["hard_give_ups"] for t in rec_trials),
            "recovery_trials": rec_trials,
            "completed_fault_window": s_fault["completed"],
            "failovers": rsum["failovers"] - rsum0["failovers"],
            "ejections": rsum["ejections"] - rsum0["ejections"],
            "readmissions": rsum["readmissions"] - rsum0["readmissions"],
            "backends_live_after": rsum["backends_live"],
            "slo_fault": s_fault["slo"],
            "slo_recovery": rec_summary["slo"],
            "slo_local_baseline": lb_summary["slo"],
            "slo_reattained": slo_ok,
            "injection_error": s_fault.get("injection_error"),
            "report_exit": rc,
            "fleet_router_line": fleet_line,
        }
        finish_class(kind, checks, (
            checks["stranded_futures_fault"] == 0
            and checks["stranded_futures_recovery"] == 0
            and checks["hard_give_ups_recovery"] == 0
            and checks["injection_error"] is None
            and s_fault["completed"] > 0      # the surviving host kept serving
            and checks["ejections"] >= 1 and checks["readmissions"] >= 1
            and rsum["backends_live"] == 2
            and slo_ok and rc == 0 and fleet_line is not None
        ))

    # backend KILL mid-traffic, with a dedup pin SPANNING the kill: the
    # pinned id's primary IS the victim, served before the window; the
    # post-kill retry (victim gone, ejected) must re-attach at the router,
    # not re-dispatch on the survivor — dedup across failover, the satellite
    pin_box: dict = {}
    kill_rid = _rid_for_primary(ports[1])
    with ServeClient(front[0], front[1], timeout_s=10.0, retries=1,
                     seed=seed) as _c:
        pin_box["rep1"] = _c.request(samples["x"][0], rid=kill_rid)
    plan = FaultPlan(
        [FaultSpec("replica_crash", at=n // 3),
         FaultSpec("worker_exception", at=n // 3)], seed=seed,
    )
    headline["fault_plan"] = plan.describe()

    def inject_kill():
        # the seeded plan's replica_crash occasion, mapped onto the offered
        # arrival clock (occasion K ~= K/rate seconds into the window)
        time.sleep(plan.specs[0].at / rate)
        backends[1].kill()

    def recover_kill():
        # retry the pinned id BEFORE respawning: the victim is dead and
        # ejected, so only the router's fleet-wide dedup can answer without
        # a second dispatch
        pin_box["pin"] = dedup_retry_pin(kill_rid, pin_box["rep1"])
        backends[1] = spawn(1)  # same port: the router re-admits the slot

    run_chaos("backend_kill", inject_kill, recover_kill)
    pin_kill = pin_box.get("pin") or {"ok": False, "error": "recover never ran"}
    finish_class("dedup_across_kill", pin_kill, pin_kill["ok"])

    # backend STALL (SIGSTOP): holds its sockets, answers nothing — the
    # router must eject on timeouts and re-admit after SIGCONT. The stall
    # outlives the health poll's 2 s read timeout twice over, so ejection
    # fires from EITHER path (deadline-capped traffic failures or two
    # consecutive poll timeouts) before the resume
    def inject_stall():
        time.sleep(plan.specs[1].at / rate)
        backends[1].stall()
        time.sleep(5.0)
        backends[1].resume()

    run_chaos("backend_stall", inject_stall, lambda: None)

    # ---------------- per-backend compile gate (absolute, always-armed) ------
    compile_gate = {}
    for b in backends:
        m = backend_poll(b.port)
        compile_gate[b.host_id] = None if m is None else m.get("compile_cache_after_warmup")
    headline["compile_cache_per_backend"] = compile_gate
    compiles_ok = all(
        isinstance(v, dict) and all(c == 0 for c in v.values())
        for v in compile_gate.values()
    ) and len(compile_gate) == 2
    finish_class("request_path_compiles", {"per_backend": compile_gate}, compiles_ok)

    # ---------------- FleetController over the router ------------------------
    ctl_events: list = []

    def controller_phase() -> dict:
        poller = FleetPoller(router)
        # drift_step 2: a deeper injected drift gives the trained-but-tiny
        # model real recoverable headroom on the drifted-family probes
        ctrl = FleetController(cfg, workdir, poller, drift_step_hint=2)
        # one traffic burst so the aggregated per-scenario stats exist, then
        # a baseline tick to anchor the windows
        with ServeClient(front[0], front[1], timeout_s=10.0) as c:
            for i in range(24):
                c.request(samples["x"][i], rid=f"ctl-{i}")
        ctl_events.append(ctrl.tick())
        epochs0 = {p: ((backend_poll(p, "health") or {}).get("swap_epoch"))
                   for p in ports}
        # drift on the aggregated stream: the harness ground-truth parity
        # feed degrades scenario 0 (the nmse_parity detector's input — the
        # confidence detectors keep watching the summed per-scenario means)
        for v in [-12.0] * 8 + [-5.5] * 10:
            ctrl.observe_parity(0, v)
        adapted = None
        for _ in range(4):
            out = ctrl.tick()
            ctl_events.append(out)
            adapted = next((e for e in out["events"]
                            if e.get("action") == "adapted"), adapted)
            if adapted:
                break
        # watch window: fresh post-deploy parity confirms, no rollback
        confirmed = None
        if adapted:
            ref = adapted["canary"]["drifted_probes"]["cand_db"]
            for _ in range(cfg.control.watch_ticks + 1):
                ctrl.observe_parity(0, ref)
                out = ctrl.tick()
                ctl_events.append(out)
                confirmed = next((e for e in out["events"]
                                  if e.get("action") == "deploy_confirmed"),
                                 confirmed)
        epochs1 = {p: ((backend_poll(p, "health") or {}).get("swap_epoch"))
                   for p in ports}
        # a single backend's ejection must never suspend adaptation for the
        # survivors: kill one host, drift a SECOND scenario, adapt again —
        # the tagged swap fans to the live backend and reports the skip
        backends[0].kill()
        time.sleep(0.3)
        router.poll_once()
        for v in [-12.0] * 8 + [-5.5] * 10:
            ctrl.observe_parity(1, v)
        adapted2 = None
        for _ in range(4):
            out = ctrl.tick()
            ctl_events.append(out)
            adapted2 = next((e for e in out["events"]
                             if e.get("action") == "adapted"), adapted2)
            if adapted2:
                break
        epochs2 = {p: ((backend_poll(p, "health") or {}).get("swap_epoch"))
                   for p in ports}
        survivor_bumped = (
            epochs2.get(ports[1]) is not None
            and epochs1.get(ports[1]) is not None
            and epochs2[ports[1]] > epochs1[ports[1]]
        )
        return {
            "drift_adapted": bool(adapted),
            "swap_fanned_all": bool(adapted) and all(
                (epochs1[p] or 0) > (epochs0[p] or 0) for p in ports
            ),
            "watch_confirmed": bool(confirmed),
            "adapted_with_ejection": bool(adapted2),
            "swap_partial_reported": bool(adapted2)
            and bool((adapted2.get("deploy") or {}).get("swap", {}).get("skipped")
                     or (adapted2.get("deploy") or {}).get("partial")),
            "survivor_swap_epoch_bumped": survivor_bumped,
            "swap_epochs": {"baseline": epochs0, "post_adapt": epochs1,
                            "post_ejected_adapt": epochs2},
        }

    ctl = controller_phase()
    finish_class("fleet_controller", ctl, (
        ctl["drift_adapted"] and ctl["swap_fanned_all"]
        and ctl["watch_confirmed"] and ctl["adapted_with_ejection"]
        and ctl["survivor_swap_epoch_bumped"]
    ))
    with open(os.path.join(out_dir, "controller_events.json"), "w") as fh:
        json.dump(ctl_events, fh, indent=2, default=str)

    # ---------------- teardown + headline ------------------------------------
    front_task.cancel()
    aloop.call_soon_threadsafe(aloop.stop)
    tloop.join(timeout=10.0)
    router.stop()
    for b in backends:
        b.terminate()
    headline["all_pass"] = all_pass
    with open(os.path.join(out_dir, "FLEET_ROUTER.json"), "w") as fh:
        json.dump(headline, fh, indent=2)
    print(json.dumps({"all_pass": all_pass}))
    return 0 if all_pass else 1


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
