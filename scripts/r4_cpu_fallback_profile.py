"""Why the CPU-fallback bench trails the torch reference (VERDICT r3 ask #7).

Run on the CPU backend:
    JAX_PLATFORMS=cpu python scripts/r4_cpu_fallback_profile.py [out.json]

BENCH_r03 recorded the XLA:CPU HDCE step at 174.5 sps vs the same host's
torch 1,385.9 (vs_baseline 0.13). This script localises the gap with paired
micro-measurements at the bench shapes and records them as the committed
evidence behind ``bench.py``'s ``cpu_fallback_note``:

1. one plain 3x3 conv (B=576, 16x8x32): XLA:CPU fwd and fwd+bwd vs torch —
   parity (XLA conv/matmul kernels are fine);
2. the SAME total work as the model actually runs it — a 3-scenario VMAPPED
   3-layer trunk — fwd+bwd under the ``conv`` lowering vs the
   ``shift_matmul`` lowering: the batched-conv gradient is the cliff
   (~5x on the trunk; 23x on a single vmapped layer vs the identical work
   unbatched);
3. the full bench HDCE f32 step under both lowerings.

The fix shipped with this script: ``ModelConfig.conv_impl = "auto"`` lowers
convs to shifted matmuls off-TPU (``qdml_tpu.models.cnn.SpatialConv``), the
formulation whose vmap is a batched matmul XLA:CPU compiles well.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from qdml_tpu.utils.platform import honor_platform_env

honor_platform_env()

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

import bench


def t_ms(f, n=3) -> float:
    f()
    r = []
    for _ in range(n):
        t0 = time.perf_counter()
        f()
        r.append(time.perf_counter() - t0)
    return round(1e3 * min(r), 1)


def conv_ref(x, k):
    return lax.conv_general_dilated(
        x, k, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC")
    )


def main() -> None:
    out: dict = {"backend": jax.default_backend(), "note": "B=576 = quarter bench batch"}
    rng = np.random.default_rng(0)
    B = 576

    # 1. plain conv parity vs torch
    x = jnp.asarray(rng.normal(size=(B, 16, 8, 32)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(3, 3, 32, 32)).astype(np.float32))
    fwd = jax.jit(conv_ref)
    out["xla_conv_fwd_ms"] = t_ms(lambda: fwd(x, k).block_until_ready())
    g = jax.jit(jax.grad(lambda x, k: jnp.sum(conv_ref(x, k) ** 2), argnums=(0, 1)))
    out["xla_conv_fwdbwd_ms"] = t_ms(lambda: jax.block_until_ready(g(x, k)))

    try:
        import torch
        import torch.nn.functional as F

        torch.set_num_threads(1)
        xt = torch.asarray(np.asarray(x).transpose(0, 3, 1, 2)).requires_grad_(True)
        kt = torch.asarray(np.asarray(k).transpose(3, 2, 0, 1)).requires_grad_(True)
        out["torch_conv_fwd_ms"] = t_ms(lambda: F.conv2d(xt, kt, padding=1))

        def tb():
            xt.grad = kt.grad = None
            F.conv2d(xt, kt, padding=1).pow(2).sum().backward()

        out["torch_conv_fwdbwd_ms"] = t_ms(tb)
    except ImportError:
        out["torch_conv_fwd_ms"] = None

    # 1b. the same single conv VMAPPED over 3 kernel instances (what the
    # stacked trunk actually lowers to): the batched-conv gradient cliff
    xs1 = jnp.asarray(rng.normal(size=(3, B // 3, 16, 8, 32)).astype(np.float32))
    ks1 = jnp.asarray(rng.normal(size=(3, 3, 3, 32, 32)).astype(np.float32))
    gv = jax.jit(
        jax.grad(lambda x, k: jnp.sum(jax.vmap(conv_ref)(x, k) ** 2), argnums=(0, 1))
    )
    out["xla_vmap3_conv_fwdbwd_ms"] = t_ms(lambda: jax.block_until_ready(gv(xs1, ks1)))

    # 2. the model's actual shape: vmapped 3-scenario trunk, conv vs shift
    from qdml_tpu.models.cnn import StackedConvP128

    xs = jnp.asarray(rng.normal(size=(3, B // 3, 16, 8, 2)).astype(np.float32))
    for impl in ("conv", "shift_matmul"):
        trunk = StackedConvP128(conv_impl=impl)
        v = trunk.init(jax.random.PRNGKey(0), xs, train=False)

        def loss(p):
            return jnp.sum(trunk.apply({"params": p["params"], "batch_stats": v["batch_stats"]}, xs, train=False) ** 2)

        gt = jax.jit(jax.grad(loss))
        out[f"vmap_trunk_{impl}_fwdbwd_ms"] = t_ms(
            lambda: jax.block_until_ready(gt(v))
        )

    # 3. full bench step under both lowerings
    for impl in ("conv", "shift_matmul"):
        try:
            out[f"bench_hdce_f32_{impl}"] = bench._bench_hdce(
                "float32", 6, 60.0, conv_impl=impl
            )
        except Exception as e:  # noqa: BLE001
            out[f"bench_hdce_f32_{impl}"] = {"error": str(e)}

    out["torch_reference_step_sps"] = bench.measure_torch_cpu_reference()

    out_path = (
        sys.argv[1] if len(sys.argv) > 1 else "results/perf_r4/cpu_fallback_profile.json"
    )
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w") as fh:
        json.dump(out, fh, indent=1)
    print(json.dumps(out, indent=1), flush=True)


if __name__ == "__main__":
    main()
