#!/bin/bash
# Seed spread for the DCE-vs-HDCE architectural gap (results/dce/).
# Two more training seeds of the reduced-protocol control study (training
# data drawn from independent generator streams via data.seed; evaluation
# stays on the COMMON default-seed test stream, the same discipline as the
# noise studies). The quantum classifier is not retrained — the gap under
# measurement is DCE-vs-HDCE, and eval falls back gracefully without a
# QSC checkpoint (Test.py:81-86 semantics).
set -e
cd /root/repo
RED="--data.data_len=4000 --train.n_epochs=30"
for s in 2 3; do
  WD=runs/science_cpu_s$s
  SEEDS="--train.seed=$s --data.seed=$((2026+s))"
  for cmd in train-hdce train-sc train-dce; do
    echo "=== seed $s $cmd ==="
    python -m qdml_tpu.cli $cmd $RED $SEEDS --train.workdir=$WD --train.resume=true
  done
  python -m qdml_tpu.cli eval --data.data_len=4000 --train.workdir=$WD \
      --eval.results_dir=results/dce/seed$s
  cp $WD/Pn_128/*/eval.metrics.jsonl results/dce/seed$s/ 2>/dev/null || true
done
echo "DCE SEEDS DONE"
