"""Single-chip high-n circuit backend microbench (VERDICT r4 -> r5 ask #3).

Run on the real TPU when the tunnel is up:
    python scripts/r5_high_n_microbench.py [out.json]

BASELINE config 3 (16-qubit sharded statevector) has a correctness story
(n=14/16 equivalence tests, the driver dryrun's sharded QSC step) but no
single-chip performance story. ``resolve_backend``
(qdml_tpu/quantum/circuits.py) switches from the dense per-ansatz unitary to
the gate-wise tensor path above n=10 on a complexity argument
(2^n x 2^n unitary build vs O(n * 2^n) gate application) that has never been
measured, and the per-layer fused Pallas rotation kernel
(``pallas_tensor``, quantum/pallas_kernels.py:365) — whose entire reason to
exist is this regime — is only correctness-tested (tests/test_pallas.py).

This session measures, at n = 8 / 10 / 12 / 14 with a fixed ~2M-amplitude
batch budget (B * 2^n = 2^21, so each point moves the same state memory):

  - forward and forward+backward WALL time per call, dense vs tensor vs
    pallas_tensor (dense capped at n <= 12: its unitary build is 2.1 GB of
    intermediates at n=14);
  - device-busy ms per call from the profiler timeline (the tunnelled
    backend adds ~1.5 ms/dispatch host gap that wall time can't separate);
  - amps/sec throughput so the points are comparable across n.

Output: the crossover table that either justifies or corrects
``resolve_backend``'s n>10 policy, committed as
results/perf_r5/high_n_microbench.json.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from qdml_tpu.utils.compile_cache import enable_compile_cache

enable_compile_cache()

import jax
import jax.numpy as jnp
import numpy as np

from r4_perf_session import device_busy_profile  # shared trace extraction

L = 3  # reference ansatz depth (Estimators_QuantumNAT_onchipQNN.py:128-138)
AMP_BUDGET = 1 << 21  # B * 2^n held constant across n

# Smoke overrides so the script's plumbing can be exercised on CPU before
# the one real tunnel window (a crash on-chip wastes the window):
# QDML_HIGHN_NS="8,10" shrinks the n sweep, QDML_HIGHN_AMPS shrinks the
# amplitude budget, QDML_HIGHN_REPS the measurement reps. UNSET, the real
# protocol is unchanged: wall reps 30, device-profile reps 20.
NS = tuple(
    int(x) for x in os.environ.get("QDML_HIGHN_NS", "8,10,12,14").split(",")
)
AMP_BUDGET = int(os.environ.get("QDML_HIGHN_AMPS", AMP_BUDGET))
_reps_env = os.environ.get("QDML_HIGHN_REPS")
WALL_REPS = int(_reps_env) if _reps_env else 30
DEV_REPS = max(4, int(_reps_env) // 2) if _reps_env else 20
SMOKE = any(os.environ.get(k) for k in ("QDML_HIGHN_NS", "QDML_HIGHN_AMPS", "QDML_HIGHN_REPS"))


def wall_us(fn, *args, reps: int = 30) -> float:
    out = fn(*args)
    float(jnp.sum(jax.tree_util.tree_leaves(out)[0]))
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    float(jnp.sum(jax.tree_util.tree_leaves(out)[0]))
    return round((time.perf_counter() - t0) / reps * 1e6, 1)


def probe(n: int, backend: str) -> dict:
    from qdml_tpu.quantum.circuits import run_circuit

    b = max(8, AMP_BUDGET >> n)  # floor rarely binds at the real budget
    rng = np.random.default_rng(0)
    angles = jnp.asarray(rng.uniform(-1, 1, (b, n)).astype(np.float32))
    w = jnp.asarray(rng.uniform(-3, 3, (L, n, 2)).astype(np.float32))

    fwd = jax.jit(lambda a, ww: run_circuit(a, ww, n, L, backend))
    bwd = jax.jit(
        jax.grad(lambda a, ww: jnp.sum(run_circuit(a, ww, n, L, backend) ** 2), (0, 1))
    )
    res = {"n": n, "backend": backend, "batch": b}
    res["fwd_wall_us"] = wall_us(fwd, angles, w, reps=WALL_REPS)
    res["fwdbwd_wall_us"] = wall_us(bwd, angles, w, reps=WALL_REPS)
    res["fwd_device"] = device_busy_profile(
        lambda: float(jnp.sum(fwd(angles, w))), reps=DEV_REPS
    )
    res["fwdbwd_device"] = device_busy_profile(
        lambda: float(jnp.sum(bwd(angles, w)[0])), reps=DEV_REPS
    )
    # throughput normalized across n: amplitudes touched per second (fwd)
    res["fwd_amps_per_s"] = round(b * (1 << n) / (res["fwd_wall_us"] / 1e6), 1)
    # trim the op lists: only the top-3 matter for the crossover story
    for k in ("fwd_device", "fwdbwd_device"):
        res[k]["top_ops"] = res[k]["top_ops"][:3]
    return res


def main() -> None:
    out_path = (
        sys.argv[1] if len(sys.argv) > 1 else "results/perf_r5/high_n_microbench.json"
    )
    out: dict = {"backend": jax.default_backend(), "layers": L, "points": []}
    if out["backend"] != "tpu" or SMOKE:
        # never let a smoke / off-chip run overwrite the committed-evidence
        # path with CPU timings and a wrong crossover verdict
        if out_path == "results/perf_r5/high_n_microbench.json":
            out_path = "/tmp/high_n_microbench_smoke.json"
        print(
            f"WARNING: smoke/off-TPU run — writing to {out_path}, not committed evidence",
            flush=True,
        )
    for n in NS:
        for backend in ("dense", "tensor", "pallas_tensor"):
            if backend == "dense" and n > 12:
                continue  # 2^14 x 2^14 unitary build: ~2.1 GB intermediates
            try:
                p = probe(n, backend)
            except Exception as e:  # noqa: BLE001
                p = {"n": n, "backend": backend, "error": f"{type(e).__name__}: {e}"}
            print(json.dumps(p)[:300], flush=True)
            out["points"].append(p)
            if os.path.dirname(out_path):
                os.makedirs(os.path.dirname(out_path), exist_ok=True)
            with open(out_path, "w") as fh:
                json.dump(out, fh, indent=1)
    # crossover summary: fastest backend per n (fwd+bwd wall — the train path)
    best: dict = {}
    for p in out["points"]:
        if "fwdbwd_wall_us" in p:
            cur = best.get(p["n"])
            if cur is None or p["fwdbwd_wall_us"] < cur[1]:
                best[p["n"]] = (p["backend"], p["fwdbwd_wall_us"])
    out["fastest_fwdbwd_by_n"] = {str(k): v[0] for k, v in sorted(best.items())}
    with open(out_path, "w") as fh:
        json.dump(out, fh, indent=1)
    print(json.dumps(out["fastest_fwdbwd_by_n"]), flush=True)


if __name__ == "__main__":
    main()
