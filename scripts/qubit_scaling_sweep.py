"""The n=4..24 qubit-scaling sweep on the virtual-device harness (ISSUE 8).

The scaling twin of the serve-fleet dryrun: force an 8-virtual-device CPU
backend (``utils.platform.force_cpu`` — the XLA_FLAGS device-count pattern),
run ``bench.py``'s ``qsc_scaling`` child over the full grid (the autotuner
races every impl eligible at each (n, topology) and the dispatcher's winner
is timed + costed per point), and round-trip the artifact through the
``qdml-tpu report`` gate. Writes ``results/qubit_scaling/``:

- ``qubit_scaling.jsonl`` — manifest-headed telemetry: the ``qsc_scaling``
  record (per-n winner, candidates, mps_chi, steps/s, XLA cost, roofline,
  numerics agreement vs an independent formulation);
- ``autotune_table.json`` — the selection table the sweep wrote: the
  committed PROOF of which impl the dispatcher picks per n on this harness;
- ``report_scaling.md`` — the rendered report (per-n best-of-impls gate rows
  + the qubit-scaling crossover section);
- ``QUBIT_SCALING.json`` — the headline (n -> impl/sps map, the n>12
  non-dense check, the report exit code).

Run: ``python scripts/qubit_scaling_sweep.py [--devices=8] [--budget=2.0]``
(~30 min on a CPU host: the n>=14 points compile grad programs with dozens
of SVDs / hundreds of collectives). Virtual-device timings measure XLA:CPU
execution, not ICI scaling — the artifact is the wiring-and-dispatch proof
(every n>12 point served by a non-dense impl, table -> record -> report gate
round-trip), the TPU re-run is the hardware headline.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from qdml_tpu.utils.platform import force_cpu  # noqa: E402


def main(argv: list[str]) -> int:
    devices = int(
        next((a.split("=", 1)[1] for a in argv if a.startswith("--devices=")), 8)
    )
    budget = next((a.split("=", 1)[1] for a in argv if a.startswith("--budget=")), None)
    force_cpu(devices)
    if budget is not None:
        os.environ["QDML_SCALING_BUDGET_S"] = budget

    import bench

    out_dir = os.path.join("results", "qubit_scaling")
    os.makedirs(out_dir, exist_ok=True)
    table = os.path.join(out_dir, "autotune_table.json")
    jsonl = os.path.join(out_dir, "qubit_scaling.jsonl")
    if os.path.exists(table):
        os.remove(table)  # the committed table must be THIS run's selections
    os.environ["QDML_SCALING_TABLE"] = table

    rc = bench.run_scaling_child(out_path=jsonl)
    if rc != 0:
        print(f"scaling child failed rc={rc}", file=sys.stderr)
        return rc

    with open(jsonl) as fh:
        record = [json.loads(ln) for ln in fh if ln.strip()][-1]
    points = record["details"]["qsc_scaling"]["points"]

    # the artifact must round-trip the regression gate: self-vs-self is the
    # committed wiring proof (exit 0); later runs gate against THIS file
    from qdml_tpu.telemetry.report import report_main

    report_rc = report_main(
        [
            f"--current={jsonl}",
            f"--baseline={jsonl}",
            f"--out={os.path.join(out_dir, 'report_scaling.md')}",
        ]
    )

    non_dense_ok = all(
        p.get("quantum_impl") not in (None, "dense", "dense_fused")
        for p in points
        if p.get("n_qubits", 0) > 12
    )
    headline = {
        "devices": devices,
        "impl_per_n": {
            str(p["n_qubits"]): {
                "impl": p.get("quantum_impl"),
                "mps_chi": p.get("mps_chi"),
                "samples_per_sec": p.get("samples_per_sec"),
                "train_ms": p.get("train_ms"),
                "agreement": p.get("agreement"),
                "error": p.get("error"),
            }
            for p in points
        },
        "non_dense_past_12": non_dense_ok,
        "report_exit": report_rc,
        "table": table,
    }
    with open(os.path.join(out_dir, "QUBIT_SCALING.json"), "w") as fh:
        json.dump(headline, fh, indent=2)
        fh.write("\n")
    print(json.dumps(headline, indent=2))
    return 0 if (report_rc == 0 and non_dense_ok) else 1


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
