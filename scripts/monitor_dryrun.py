"""Flight-deck monitoring dryrun over REAL backend serve processes (ISSUE 16).

The live proof of the continuous monitor (docs/TELEMETRY.md "flight
deck"): spawn 2 genuine ``qdml-tpu serve`` processes, front them with a
:class:`FleetRouter` (trace bit ON so every window carries phase spans),
attach a :class:`MonitorScraper` to the front door over the health/metrics
verbs only, and drive marked traffic segments through it — healthy
baseline, an idle probe, an injected backend STALL (SIGSTOP mid-window),
and a recovery window. Every gate is absolute/invariant (no %-latency
rows — the monitor judges behavior, not this harness's tail noise):

- **paging discipline**: a burn-rate alert FIRES during the injected-stall
  segment and NEVER during the healthy baseline or the idle probe (the
  committed ``monitor.jsonl`` carries the transitions; ``qdml-tpu report``
  re-arms the same expectation from the summary's ``expect`` block);
- **scrape discipline, proven twice**: the monitor's poller is wrapped in
  a verb audit (health/metrics only — anything else would AttributeError
  into a scrape_error), and an idle monitored segment leaves every
  backend's own completed counter bitwise unchanged while scrapes keep
  landing; post-run, each backend's ``compile_cache_after_warmup`` delta
  is all-zero (monitoring rides the observability path, never inference);
- **timeline correlation**: ``monitor --render`` shows the stall
  segment's alert annotated with the router's ejection/readmission events
  on the same clock (the router's global-sink events land in the monitor
  stream itself);
- **planner validation**: the trace-replay capacity model self-replays
  the committed trace_dryrun + fleet_router windows AND this run's fresh
  traced windows inside the documented band, and the planning sweep
  answers a "hosts for X rps at p99 <= Y ms" question with a concrete
  fleet size;
- **report round-trip exit 0** with the monitoring section's always-armed
  gates (alert expectations + planner band) green.

Writes ``results/monitor_dryrun/``: ``monitor.jsonl`` (the attachment
stream), ``baseline_t0/stall_t0/recovery_t0.jsonl`` (traffic windows),
``timeline.md``, ``report_monitor.md``, ``MONITOR_DRYRUN.json``.

Run: ``python scripts/monitor_dryrun.py [--n=240] [--rate=60]
[--deadline-ms=500] [--seed=0]``
"""

from __future__ import annotations

import glob
import json
import os
import socket
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from qdml_tpu.utils.platform import force_cpu  # noqa: E402


def _arg(argv, name, default):
    return next((a.split("=", 1)[1] for a in argv if a.startswith(f"--{name}=")), default)


def _free_port() -> int:
    with socket.socket() as sk:
        sk.bind(("127.0.0.1", 0))
        return sk.getsockname()[1]


class VerbAuditPoller:
    """The monitor's poller, pinned: ONLY the observability verbs exist on
    this object — a scraper reaching for request/swap/scale would
    AttributeError into its scrape_error path, and the audit set proves
    what it actually used."""

    def __init__(self, inner):
        self._inner = inner
        self.calls: set = set()

    def health(self):
        self.calls.add("health")
        return self._inner.health()

    def metrics(self):
        self.calls.add("metrics")
        return self._inner.metrics()


def main(argv: list[str]) -> int:
    n = int(_arg(argv, "n", "240"))
    rate = float(_arg(argv, "rate", "60"))
    deadline_ms = float(_arg(argv, "deadline-ms", "500"))
    threshold = _arg(argv, "threshold", "50")
    seed = int(_arg(argv, "seed", "0"))
    force_cpu(2)

    import asyncio
    import dataclasses
    from concurrent.futures import Future

    from qdml_tpu.config import (
        ControlConfig,
        DataConfig,
        ExperimentConfig,
        ModelConfig,
        ServeConfig,
        TrainConfig,
    )
    from qdml_tpu.control.loop import SocketPoller
    from qdml_tpu.fleet import FleetRouter, route_async, spawn_backend
    from qdml_tpu.serve import ServeClient, make_request_samples, run_loadgen_socket
    from qdml_tpu.telemetry import run_manifest, set_sink
    from qdml_tpu.telemetry.burnrate import BurnAlerter, BurnRateRule
    from qdml_tpu.telemetry.capacity import (
        load_summary,
        plan_backends,
        validate_windows,
    )
    from qdml_tpu.telemetry.report import report_main
    from qdml_tpu.telemetry.timeseries import MonitorScraper, monitor_main
    from qdml_tpu.train.hdce import train_hdce
    from qdml_tpu.train.qsc import train_classifier
    from qdml_tpu.utils.metrics import MetricsLogger

    out_dir = os.path.join("results", "monitor_dryrun")
    os.makedirs(out_dir, exist_ok=True)
    for stale in glob.glob(os.path.join(out_dir, "*.jsonl")):
        os.remove(stale)  # telemetry streams APPEND: a prior run's records
        # would smuggle its alerts/windows into this run's gates
    scratch = tempfile.mkdtemp(prefix="monitor_")

    cfg = ExperimentConfig(
        name="monitor_dryrun",
        data=DataConfig(n_ant=16, n_sub=8, n_beam=4, data_len=64),
        model=ModelConfig(features=8),
        train=TrainConfig(batch_size=16, n_epochs=8, workdir=scratch, probe_every=0),
        serve=ServeConfig(
            max_batch=16, buckets=(4, 16), max_wait_ms=2.0, max_queue=64,
            batching="bucket", dedup_ttl_s=10.0, conn_timeout_s=5.0,
            supervise=True,
        ),
        control=ControlConfig(min_window=6, autoscale=False),
    )
    workdir = os.path.join(scratch, f"Pn_{cfg.data.pilot_num}", cfg.name)
    print("training fleet models (8-epoch HDCE + 8-epoch SC) ...", flush=True)
    tlog = MetricsLogger(os.path.join(scratch, "train.jsonl"), echo=False,
                        manifest=run_manifest(cfg))
    try:
        train_hdce(cfg, logger=tlog, workdir=workdir)
        sc_cfg = dataclasses.replace(
            cfg, train=dataclasses.replace(cfg.train, n_epochs=8)
        )
        train_classifier(sc_cfg, quantum=False, logger=tlog, workdir=workdir)
    finally:
        tlog.close()
    samples = make_request_samples(cfg, int(n * 1.5))  # the stall window
    # runs 1.5x long so the fault + debounce + page resolve inside it

    backend_overrides = [
        "--name=monitor_dryrun",
        "--data.n_ant=16", "--data.n_sub=8", "--data.n_beam=4",
        "--data.data_len=64", "--model.features=8", "--train.batch_size=16",
        f"--train.workdir={scratch}",
        "--serve.max_batch=16", "--serve.buckets=(4,16)",
        "--serve.max_wait_ms=2.0", "--serve.max_queue=64",
        "--serve.batching=bucket", "--serve.dedup_ttl_s=10.0",
        "--serve.conn_timeout_s=5.0", "--serve.supervise=true",
        # the ROUTER's trace bit turns tracing on; backends sample at 0
        "--serve.trace_sample=0.0",
    ]
    ports = [_free_port(), _free_port()]

    def spawn(i: int):
        print(f"spawning backend {i} on :{ports[i]} ...", flush=True)
        b = spawn_backend(backend_overrides, port=ports[i])
        print(json.dumps({"backend": i, "port": b.port, "host_id": b.host_id,
                          "compiles_after_warmup": b.banner[
                              "compile_cache_after_warmup"]}), flush=True)
        return b

    backends = [spawn(0), spawn(1)]
    router = FleetRouter(
        [("127.0.0.1", p) for p in ports],
        balance="hash", timeout_s=1.0, retries=0,
        eject_failures=2, eject_s=0.5, readmit_probes=1,
        poll_interval_s=0.2, failover=2, seed=seed,
        dedup_ttl_s=120.0,
        trace_sample=1.0,  # every window carries phase spans: the fresh
        # windows join the committed set in the planner's validation gate
    ).start()
    aloop = asyncio.new_event_loop()
    tloop = threading.Thread(target=aloop.run_forever, daemon=True)
    tloop.start()
    ready: Future = Future()
    front_task = asyncio.run_coroutine_threadsafe(
        route_async(router, "127.0.0.1", 0, ready,
                    conn_timeout_s=5.0, max_line_bytes=1 << 20),
        aloop,
    )
    front = ("127.0.0.1", ready.result(timeout=30.0))
    print(json.dumps({"router_front": front[1]}), flush=True)

    # -------- attach the monitor (health/metrics only, audited) -----------
    mon_path = os.path.join(out_dir, "monitor.jsonl")
    mlog = MetricsLogger(mon_path, echo=False, manifest=run_manifest(cfg))
    # the router's structured fleet events (backend_ejected/readmitted) go
    # to the process-global sink: point it at the monitor stream so the
    # timeline correlates alerts with the stack's own events on one clock
    set_sink(mlog.telemetry)
    audit = VerbAuditPoller(SocketPoller(front[0], front[1], timeout_s=5.0))
    alerter = BurnAlerter.for_run(duration_s=30.0, interval_s=0.4,
                                  slo_target=0.99, threshold=8.0, debounce=2)
    # harness-scaled router rule: a fast-ejecting router (eject_failures=2,
    # 1s timeouts) caps the failover fraction a 3-second stall can produce
    # at ~10-13% of forwards — burn ~5-6x on the 0.02 budget — and the page
    # must fire AND the slow window must fill inside one short window, so
    # the router pair runs tighter/lower than the production-shaped default
    # (a real deployment keeps for_run's scaling). Budget and mechanics are
    # unchanged; only the pair's geometry is scaled to the run.
    alerter.rules["router"] = BurnRateRule(
        "router", budget=0.02, fast_s=1.2, slow_s=3.6,
        threshold=3.0, debounce=2,
    )
    scraper = MonitorScraper(audit, sink=mlog.telemetry, interval_s=0.4,
                             alerter=alerter)
    stop_mon = threading.Event()
    scraper.mark("baseline_t0")
    mon_thread = threading.Thread(
        target=scraper.run, args=(600.0,), kwargs={"stop": stop_mon},
        daemon=True,
    )
    mon_thread.start()

    window_seq = [0]

    def serve_window(tag: str, n_win: int, during=None):
        side_err: list = []
        side = None
        if during is not None:
            def _side():
                try:
                    during()
                except Exception as e:  # lint: disable=broad-except(the injection side thread must report its failure into the headline, not die silently and fake a passing run)
                    side_err.append(f"{type(e).__name__}: {e}")
            side = threading.Thread(target=_side, daemon=True)
            side.start()
        path = os.path.join(out_dir, f"{tag}.jsonl")
        logger = MetricsLogger(path, echo=False, manifest=run_manifest(cfg))
        window_seq[0] += 1  # fresh loadgen ids per window (dedup discipline)
        try:
            summary = run_loadgen_socket(
                cfg, front, rate=rate, n=n_win,
                seed=seed + 1000 * window_seq[0],
                deadline_ms=deadline_ms, logger=logger, clients=8,
                x=samples["x"],
            )
        finally:
            logger.close()
        if side is not None:
            side.join(timeout=60.0)
        if side_err:
            summary["injection_error"] = side_err[0]
        return summary, path

    def backend_poll(port: int, verb: str = "metrics") -> dict | None:
        try:
            with ServeClient("127.0.0.1", port, timeout_s=5.0, retries=1) as c:
                rep = c.metrics() if verb == "metrics" else c.health()
                return rep.get(verb)
        except Exception:  # lint: disable=broad-except(a dead/stalled backend is an expected poll outcome here; the caller records None)
            return None

    def per_port_completed() -> dict:
        out = {}
        for p in ports:
            m = backend_poll(p)
            out[p] = None if m is None else int(m.get("completed") or 0)
        return out

    headline: dict = {
        "n": n, "rate": rate, "deadline_ms": deadline_ms, "seed": seed,
        "monitor": {"interval_s": scraper.interval_s,
                    "burn_windows": {
                        s: {"fast_s": r.fast_s, "slow_s": r.slow_s,
                            "threshold": r.threshold, "budget": r.budget}
                        for s, r in alerter.rules.items()
                    }},
        "backends": {b.host_id: {"port": b.port} for b in backends},
        "classes": {},
    }
    all_pass = True

    def finish_class(kind: str, checks: dict, ok: bool) -> None:
        nonlocal all_pass
        checks["ok"] = ok
        headline["classes"][kind] = checks
        all_pass = all_pass and ok
        print(json.dumps({kind: {"ok": ok}}), flush=True)

    # -------- baseline segment: healthy fleet under the monitor ----------
    base_summary, base_path = serve_window("baseline_t0", n)
    time.sleep(1.2)  # stream drains; any late window still carries this mark
    finish_class("baseline", {
        "completed": base_summary["completed"],
        "stranded_futures": base_summary["stranded_futures"],
        "slo": base_summary["slo"],
        "path": base_path,
    }, base_summary["stranded_futures"] == 0 and base_summary["completed"] > 0)

    # -------- idle probe: the scrape path adds ZERO requests --------------
    scraper.mark("idle_probe")
    seq0 = scraper.seq
    before_idle = per_port_completed()
    time.sleep(2.5)
    after_idle = per_port_completed()
    idle_scrapes = scraper.seq - seq0
    idle_ok = (
        idle_scrapes >= 2
        and all(before_idle[p] is not None and after_idle[p] == before_idle[p]
                for p in ports)
    )
    finish_class("scrape_inference_free_idle", {
        "scrapes_during_idle": idle_scrapes,
        "completed_before": before_idle,
        "completed_after": after_idle,
    }, idle_ok)

    # -------- injected stall: the monitor must page ----------------------
    scraper.mark("stall_t0")

    def inject_stall():
        time.sleep(1.0)
        backends[1].stall()
        time.sleep(3.0)
        backends[1].resume()

    stall_summary, stall_path = serve_window(
        "stall_t0", int(n * 1.5), during=inject_stall
    )
    time.sleep(2.0)  # late burn transitions still attribute to stall_t0

    # router re-admits the resumed backend before the recovery window
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline and len(router.live_backends()) < 2:
        router.poll_once()
        time.sleep(0.1)

    scraper.mark("recovery_t0")
    rec_summary, rec_path = serve_window("recovery_t0", n)
    time.sleep(1.2)
    stop_mon.set()
    mon_thread.join(timeout=15.0)

    fired_marks = sorted({
        a.get("mark") for a in scraper.alerts if a.get("state") == "firing"
    })
    alert_ok = (
        "stall_t0" in fired_marks
        and "baseline_t0" not in fired_marks
        and "idle_probe" not in fired_marks
        and stall_summary.get("injection_error") is None
    )
    finish_class("burn_alert_paging", {
        "fired_marks": fired_marks,
        "alerts": list(scraper.alerts),
        "peak_burn": alerter.peaks(),
        "stall_window": {
            "completed": stall_summary["completed"],
            "stranded_futures": stall_summary["stranded_futures"],
            "slo": stall_summary["slo"],
        },
        "injection_error": stall_summary.get("injection_error"),
        "backends_live_after": len(router.live_backends()),
    }, alert_ok and stall_summary["stranded_futures"] == 0
       and len(router.live_backends()) == 2)

    # -------- scrape discipline: verbs + per-backend compile deltas -------
    verbs = sorted(audit.calls)
    compile_gate = {}
    for b in backends:
        m = backend_poll(b.port)
        compile_gate[b.host_id] = None if m is None else m.get(
            "compile_cache_after_warmup")
    compiles_ok = all(
        isinstance(v, dict) and all(c == 0 for c in v.values())
        for v in compile_gate.values()
    ) and len(compile_gate) == 2
    finish_class("scrape_verbs_and_compiles", {
        "verbs_used": verbs,
        "per_backend_compiles": compile_gate,
        "scrape_errors": scraper.scrape_errors,
    }, verbs == ["health", "metrics"] and compiles_ok)

    # -------- capacity planner: validate committed + fresh windows --------
    committed = sorted(glob.glob(os.path.join(
        "results", "trace_dryrun", "traced_t*.jsonl"
    ))) + sorted(glob.glob(os.path.join(
        "results", "fleet_router", "baseline*.jsonl"
    )))
    fresh = [base_path, rec_path]
    validation = validate_windows(committed + fresh, n_samples=8000, seed=seed)
    # the planning demo: answer a real question against this run's own
    # traced baseline — target above the window's exogenous floor (adders
    # the fleet size cannot shrink), so the sweep must resolve a size
    meas = load_summary(base_path)
    meas_p99 = float((meas.get("latency_ms") or {}).get("p99_ms") or 100.0)
    plan = plan_backends(
        base_path, target_rps=float(meas.get("rps") or rate),
        p99_ms=meas_p99 * 1.6, max_backends=6, n_samples=3000, seed=seed,
    )
    plan_ok = plan["backends_needed"] is not None
    finish_class("planner", {
        "validation": {k: v for k, v in validation.items() if k != "rows"},
        "windows": [r["path"] for r in validation["rows"]],
        "plan_demo": {"target_rps": plan["target_rps"],
                      "p99_target_ms": plan["p99_target_ms"],
                      "backends_needed": plan["backends_needed"]},
    }, validation["ok"] and plan_ok)

    # -------- summary + timeline + report round-trip ----------------------
    expect = {"fired": ["stall_t0"], "quiet": ["baseline_t0", "idle_probe"]}
    scraper.finish(extra={"expect": expect, "planner": validation,
                          "plan_demo": plan})
    set_sink(None)
    mlog.close()

    timeline_path = os.path.join(out_dir, "timeline.md")
    rc_render = monitor_main([
        "--render", f"--current={mon_path}", f"--events={stall_path}",
        f"--out={timeline_path}",
    ])
    with open(timeline_path) as fh:
        timeline = fh.read()
    timeline_ok = (
        rc_render == 0
        and "**ALERT" in timeline
        and ("backend_ejected" in timeline or "backend_readmitted" in timeline)
        and "capacity-planner validation: PASS" in timeline
    )
    finish_class("timeline", {
        "path": timeline_path,
        "render_exit": rc_render,
        "has_alert_row": "**ALERT" in timeline,
        "has_stack_event": "backend_ejected" in timeline
        or "backend_readmitted" in timeline,
    }, timeline_ok)

    # round-trip 1 (exit-code plumbing, repo self-vs-self pattern): the
    # committed baseline + monitor stream against the baseline itself must
    # exit 0 WITH the monitor gates armed — a monitor_failed would flip it
    report_md = os.path.join(out_dir, "report_monitor.md")
    rc = report_main([
        f"--current={base_path},{mon_path}", f"--baseline={base_path}",
        f"--threshold={threshold}", f"--out={report_md}",
        f"--json={os.path.join(out_dir, 'report_monitor.json')}",
    ])
    with open(report_md) as fh:
        monitor_lines = [ln.strip() for ln in fh if "alert expectation" in ln
                         or "planner validation" in ln]
    # round-trip 2 (the CI stage's judgment, scripts/run_tier1.sh): the
    # recovery window judged on INVARIANT + monitor rows only — %-latency
    # rows between two windows on this 2-core harness are contention noise,
    # which is exactly why the tier-1 stage reads the JSON rows, not the rc
    rec_json = os.path.join(out_dir, "report_recovery.json")
    report_main([
        f"--current={rec_path},{mon_path}", f"--baseline={base_path}",
        f"--threshold={threshold}",
        f"--out={os.path.join(out_dir, 'report_recovery.md')}",
        f"--json={rec_json}",
    ])
    with open(rec_json) as fh:
        rec_gate = json.load(fh)
    invariant_kinds = ("resilience", "breaker", "dispatch", "batching",
                      "monitor")
    invariants_ok = (
        not rec_gate.get("stranded_failed")
        and not rec_gate.get("monitor_failed")
        and not any(
            g.get("status") == "regression" and g.get("kind") in invariant_kinds
            for g in rec_gate.get("gates", [])
        )
    )
    finish_class("report_roundtrip", {
        "selfcheck_exit": rc,
        "monitor_gate_lines": monitor_lines,
        "recovery_invariants_ok": invariants_ok,
    }, rc == 0 and invariants_ok and len(monitor_lines) >= 4)

    # -------- teardown + headline ----------------------------------------
    front_task.cancel()
    aloop.call_soon_threadsafe(aloop.stop)
    tloop.join(timeout=10.0)
    router.stop()
    for b in backends:
        b.terminate()
    headline["all_pass"] = all_pass
    with open(os.path.join(out_dir, "MONITOR_DRYRUN.json"), "w") as fh:
        json.dump(headline, fh, indent=2, default=str)
    print(json.dumps({"all_pass": all_pass}))
    return 0 if all_pass else 1


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
