#!/bin/bash
# Tunnel watcher (round 5): probe the axon backend every ~2.5 min with a
# hard timeout (a DOWN tunnel hangs at backend init; outages last hours).
# When a probe computes on a real TPU, fire the r5 session. If the tunnel
# dropped mid-session (artifacts incomplete), go back to probing and
# re-fire — every session phase is resume-capable / idempotent — up to
# MAX_FIRES times. Logs to /tmp/r5_watch.log; sessions to
# /tmp/r5_session_N.log.
cd /root/repo
LOG=/tmp/r5_watch.log
START_MARK=/tmp/r5_watch_start
touch "$START_MARK"
PROBE='import jax, jax.numpy as jnp; assert jax.default_backend()=="tpu", jax.default_backend(); print("probe-ok", int(jnp.ones((8,8)).sum()))'
# Fires are cheap now: the session probes tunnel liveness at every phase
# boundary and exits in ~60 s when the tunnel dropped (r5 hardening), so a
# flapping tunnel burns a fire per flap without doing hours of work — the
# cap exists only to bound a pathological loop, not to ration real windows.
MAX_FIRES=12
fires=0

complete() {
  # all phase artifacts present and fresher than watcher start
  [ -f results/bench_tpu_v5e_r5.json ] || return 1
  grep -q '"pallas_wins"' results/perf_r5/r5_perf_session.json 2>/dev/null || return 1
  grep -q '"fast_wins"' results/perf_r5/scan_ab.json 2>/dev/null || return 1
  grep -q fastest_fwdbwd_by_n results/perf_r5/high_n_microbench.json 2>/dev/null || return 1
  [ results/dce/results_table.md -nt "$START_MARK" ] || return 1
  [ results/dce/seed2/results_table.md -nt "$START_MARK" ] || return 1
  return 0
}

echo "$(date -u +%F' '%T) watcher start" >> "$LOG"
while true; do
  if timeout 90 env JAX_PLATFORMS=axon python -c "$PROBE" >> "$LOG" 2>&1; then
    fires=$((fires + 1))
    echo "$(date -u +%F' '%T) tunnel UP — firing r5 session (#$fires)" >> "$LOG"
    bash scripts/r5_tpu_session.sh > "/tmp/r5_session_$fires.log" 2>&1
    rc=$?
    echo "$(date -u +%F' '%T) session #$fires done rc=$rc" >> "$LOG"
    if complete; then
      echo "$(date -u +%F' '%T) all artifacts complete — watcher exiting" >> "$LOG"
      exit 0
    fi
    if [ "$fires" -ge "$MAX_FIRES" ]; then
      echo "$(date -u +%F' '%T) max fires reached with incomplete artifacts" >> "$LOG"
      exit 1
    fi
    echo "$(date -u +%F' '%T) artifacts incomplete — resuming watch" >> "$LOG"
  else
    echo "$(date -u +%F' '%T) tunnel down" >> "$LOG"
  fi
  sleep 150
done
