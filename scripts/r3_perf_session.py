"""On-chip perf session: extended pallas-vs-dense A/B + generator-RNG A/B.

Run on the real TPU when the tunnel is up:
    python scripts/r3_perf_session.py [out.json]

Two questions, both noise-sensitive on the tunnelled single-chip backend
(single measurements swing ~25% run-to-run, docs/ROOFLINE.md), so both are
answered with ALTERNATING A/B pairs — each round measures the contenders
back-to-back under the same machine state, and the verdict is per-round wins
plus medians, not one number:

1. QSC train step, pallas whole-circuit kernel vs XLA dense — at every
   published qubit count (4/6/8, reference ``Loss Curve.png`` legend;
   the kernel's VMEM budget covers n<=8, ``circuits.resolve_backend``).
   Extends the committed 4-round n=6 A/B (results/perf_r3/r3_qsc_ab.json).
2. Scan-fused HDCE training (train.scan_steps=16) with the threefry vs
   hardware-RBG generator stream (DataConfig.rng_impl) — in-scan synthesis
   pays for its random bits on device (~5.5M normal draws per 2304-sample
   batch, dominated by the 2x1024/sample label noise), so the PRNG is a
   real throughput lever.
"""

import json
import os
import statistics
import sys

# Repo root on sys.path BEFORE any repo import: `python scripts/foo.py` puts
# scripts/ (not the root) there, and qdml_tpu is not installed.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from qdml_tpu.utils.compile_cache import enable_compile_cache

enable_compile_cache()

import jax

import bench


def ab(name: str, contenders: dict, rounds: int, out: dict) -> None:
    """Alternating A/B: run each contender once per round, record sps."""
    results = {k: [] for k in contenders}
    errors: list[str] = []
    for r in range(rounds):
        for k, fn in contenders.items():
            try:
                sps = fn()["samples_per_sec"]
            except Exception as e:  # noqa: BLE001
                sps = None
                errors.append(f"{k}@{r}: {e}")
            results[k].append(sps)
        print(f"[{name}] round {r}: " + ", ".join(f"{k}={results[k][-1]}" for k in contenders), flush=True)
    summary = {"rounds": results}
    if errors:
        summary["errors"] = errors
    keys = [k for k in contenders if any(v is not None for v in results[k])]
    for k in keys:
        vals = [v for v in results[k] if v is not None]
        summary[f"{k}_med"] = round(statistics.median(vals), 1)
    if len(keys) == 2:
        a, b = keys
        wins = sum(
            1
            for x, y in zip(results[a], results[b])
            if x is not None and y is not None and x > y
        )
        summary[f"{a}_wins"] = wins
        summary["n_pairs"] = sum(
            1 for x, y in zip(results[a], results[b]) if x is not None and y is not None
        )
    out[name] = summary


def main() -> None:
    print("backend:", jax.default_backend(), flush=True)
    out = {"backend": jax.default_backend()}

    # 1. pallas vs dense at each published qubit count, via the bench
    #    harness's own builder so both measure exactly the program bench.py
    #    records.
    def qsc_step_bench(backend: str, n_qubits: int):
        return bench._bench_qsc(backend, 50, 30.0, n_qubits=n_qubits)

    for n in (4, 6, 8):
        rounds = 8 if n == 6 else 4
        ab(
            f"qsc_n{n}",
            {
                "pallas": lambda n=n: qsc_step_bench("pallas", n),
                "dense": lambda n=n: qsc_step_bench("dense", n),
            },
            rounds,
            out,
        )

    # 2. scan-fused HDCE: threefry vs rbg generator stream.
    ab(
        "hdce_scan_rng",
        {
            "rbg": lambda: bench._bench_hdce_scan("bfloat16", 16, 50, 60.0, rng_impl="rbg"),
            "threefry": lambda: bench._bench_hdce_scan("bfloat16", 16, 50, 60.0),
        },
        4,
        out,
    )

    out_path = sys.argv[1] if len(sys.argv) > 1 else "results/perf_r3/r3_perf_session.json"
    with open(out_path, "w") as fh:
        json.dump(out, fh, indent=1)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
