"""Seeded chaos-injection dryrun over the fault-tolerant serving stack (ISSUE 13).

The resilience twin of the committed serving dryruns: force the virtual-CPU
backend, warm ONE engine, then for EVERY fault class in
``qdml_tpu.serve.faults.FAULT_CLASSES`` stand up a fresh supervised
2-replica pool behind the hardened socket front-end, drive a measured
traffic window WHILE the fault fires (worker faults through the seeded
:class:`FaultPlan` hooks; socket faults as raw misbehaving clients; file
faults against the checkpoint workdir / a scratch autotune table), then a
recovery window — and prove, per class:

- **zero stranded futures** (every offered request reached a typed closure;
  the always-armed report gate),
- **zero request-path compiles** (the engine's cumulative post-warmup
  counter delta, checked after the LAST class — chaos never compiles),
- **SLO re-attainment after recovery** (the recovery window's attainment
  against the pre-chaos baseline through the ``qdml-tpu report`` gate,
  exit 0 required),
- the class-specific behavior (restart/quarantine events, typed
  ``swap_failed`` on a corrupt checkpoint, idle reaps, dedup'd retries).

Writes ``results/chaos_dryrun/``:

- ``baseline[_tN].jsonl`` — the no-fault steady windows (manifest-headed;
  best-of-3 by p99 anchors the headline);
- ``{class}_fault.jsonl`` — the window the fault fires in;
- ``{class}_recovery_tN.jsonl`` / ``{class}_base_tN.jsonl`` — interleaved
  recovery and CONTEMPORANEOUS no-fault baseline trials (host load drifts
  over the minutes the matrix runs; adjacent windows are the only honest
  %-threshold comparison — behavior checks hold on EVERY trial);
- ``report_{class}.md`` — the rendered recovery-vs-local-baseline gate;
- ``CHAOS_DRYRUN.json`` — the headline: per-class checks + all_pass.

Run: ``python scripts/chaos_dryrun.py [--n=160] [--rate=400]
[--deadline-ms=50] [--devices=2] [--seed=0] [--classes=a,b,...]
[--out-dir=DIR]``

``--classes`` restricts the matrix to a subset of fault classes (the
``QDML_LOCKDEP=1`` witness re-run and the tier-1 smoke use this);
``--out-dir`` redirects the artifact tree — the committed
``results/chaos_dryrun/`` windows that ``run_tier1.sh`` stage-2 gates over
must never be overwritten by a partial re-run. The headline always carries
a ``lockdep`` block (:func:`qdml_tpu.utils.lockdep.witness_summary`): with
``QDML_LOCKDEP=1`` the run fails unless zero lock-order inversions were
witnessed across every injected crash, restart, and swap.

Virtual-device timings measure supervision/retry/protocol behavior, not
ICI; on a real pod the same script re-runs and the same gates arm on TPU
numbers.
"""

from __future__ import annotations

import json
import os
import socket
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from qdml_tpu.utils.platform import force_cpu  # noqa: E402


def _arg(argv: list[str], name: str, default: str) -> str:
    return next((a.split("=", 1)[1] for a in argv if a.startswith(f"--{name}=")), default)


def main(argv: list[str]) -> int:
    devices = int(_arg(argv, "devices", "2"))
    n = int(_arg(argv, "n", "400"))
    rate = float(_arg(argv, "rate", "400"))
    deadline_ms = float(_arg(argv, "deadline-ms", "50"))
    # Report threshold for the recovery-vs-baseline gates. The chaos gates
    # that MATTER are absolute/invariant and ignore this entirely: stranded
    # futures == 0 (always-armed), breaker open fraction (+0.05 absolute),
    # and SLO re-attainment, which this script checks EXPLICITLY below
    # (recovery attainment within 0.05 of the contemporaneous baseline's —
    # never diluted by the threshold). The %-threshold rows (p50/p99/
    # goodput) compare IDENTICAL code across windows, where a contended
    # 2-core host's p99 minima swing ±30-50% between adjacent runs — 50%
    # documents "recovered to the same regime" without a coin-flip CI. On
    # real hardware re-runs, tighten back toward the default 10%.
    threshold = _arg(argv, "threshold", "50")
    seed = int(_arg(argv, "seed", "0"))
    only_classes = [c for c in _arg(argv, "classes", "").split(",") if c]
    out_dir = _arg(argv, "out-dir", os.path.join("results", "chaos_dryrun"))
    force_cpu(devices)

    import asyncio
    from concurrent.futures import Future

    from qdml_tpu.config import (
        DataConfig,
        ExperimentConfig,
        ModelConfig,
        ServeConfig,
        TrainConfig,
    )
    from qdml_tpu.serve import (
        FAULT_CLASSES,
        FaultPlan,
        FaultSpec,
        ReplicaPool,
        ServeClient,
        ServeEngine,
        make_request_samples,
        run_loadgen_socket,
        serve_async,
    )
    from qdml_tpu.serve import batching_autotune
    from qdml_tpu.telemetry import run_manifest
    from qdml_tpu.telemetry.report import report_main
    from qdml_tpu.train.checkpoint import save_checkpoint
    from qdml_tpu.train.hdce import init_hdce_state
    from qdml_tpu.train.qsc import init_sc_state
    from qdml_tpu.utils import lockdep
    from qdml_tpu.utils.metrics import MetricsLogger

    run_classes = list(FAULT_CLASSES)
    if only_classes:
        unknown = [c for c in only_classes if c not in FAULT_CLASSES]
        if unknown:
            print(f"chaos_dryrun: unknown --classes {unknown}; "
                  f"valid: {list(FAULT_CLASSES)}")
            return 2
        run_classes = only_classes

    os.makedirs(out_dir, exist_ok=True)
    scratch = tempfile.mkdtemp(prefix="chaos_")

    # The raced batching table lives on scratch for this run: the
    # autotune_corrupt class corrupts it mid-run, and the COMMITTED table
    # under results/autotune must never be the victim.
    batching_autotune.set_table_path(os.path.join(scratch, "serve_batching.json"))

    cfg = ExperimentConfig(
        name="chaos_dryrun",
        data=DataConfig(n_ant=16, n_sub=8, n_beam=4, data_len=64),
        model=ModelConfig(features=8),
        train=TrainConfig(batch_size=16, n_epochs=1),
        serve=ServeConfig(
            max_batch=16,
            buckets=(4, 16),
            max_wait_ms=2.0,
            max_queue=64,
            batching="auto",          # the measured race, on the scratch table
            breaker=True,             # brownout armed; counters flow to gates
            breaker_high_frac=0.9,
            breaker_low_frac=0.3,
            supervise=True,
            supervise_interval_s=0.02,
            restart_backoff_s=0.01,
            restart_budget=3,
            conn_timeout_s=1.0,       # fast idle reap for the stalled_client class
            max_line_bytes=1 << 20,
            dedup_ttl_s=10.0,
        ),
    )

    _, hdce_state = init_hdce_state(cfg, 4)
    hdce_vars = {"params": hdce_state.params, "batch_stats": hdce_state.batch_stats}
    _, sc_state = init_sc_state(cfg, quantum=False, steps_per_epoch=4)
    clf_vars = {"params": sc_state.params}
    # checkpoint workdir for the corrupt_swap class: healthy tags + one tag
    # directory that exists but holds garbage instead of a checkpoint
    workdir = os.path.join(scratch, "ckpt")
    save_checkpoint(workdir, "hdce_last", hdce_vars)
    save_checkpoint(workdir, "sc_last", clf_vars)
    bad_tag = os.path.join(workdir, "hdce_bad")
    os.makedirs(bad_tag)
    with open(os.path.join(bad_tag, "_METADATA"), "w") as fh:
        fh.write("garbage, not an orbax checkpoint")

    engine = ServeEngine(cfg, hdce_vars, clf_vars)
    samples = make_request_samples(cfg, n)
    warm = engine.warmup()

    def serve_window(pool, tag: str, during=None):
        """One served traffic window behind a fresh socket front-end;
        ``during(port)`` runs on a side thread while traffic flows (the
        socket/file fault injections)."""
        aloop = asyncio.new_event_loop()
        t = threading.Thread(target=aloop.run_forever, daemon=True)
        t.start()
        ready: Future = Future()
        task = asyncio.run_coroutine_threadsafe(
            serve_async(
                pool, "127.0.0.1", 0, ready,
                swap_fn=lambda tags=None: engine.swap_from_workdir(workdir, tags=tags),
            ),
            aloop,
        )
        port = ready.result(timeout=30.0)
        side_err: list = []
        side = None
        if during is not None:
            def _side():
                try:
                    during(port)
                except Exception as e:  # lint: disable=broad-except(the injection side thread must report its failure into the headline, not die silently and fake a passing chaos run)
                    side_err.append(f"{type(e).__name__}: {e}")
            side = threading.Thread(target=_side, daemon=True)
            side.start()
        path = os.path.join(out_dir, f"{tag}.jsonl")
        logger = MetricsLogger(path, echo=False, manifest=run_manifest(cfg))
        try:
            summary = run_loadgen_socket(
                cfg, ("127.0.0.1", port), rate=rate, n=n, seed=seed,
                deadline_ms=deadline_ms, logger=logger, clients=8, x=samples["x"],
            )
        finally:
            logger.close()
        if side is not None:
            side.join(timeout=30.0)
        task.cancel()
        try:
            task.result(timeout=5.0)
        except Exception:  # lint: disable=broad-except(teardown: the cancelled server task resolves with CancelledError by design; any other shutdown error is uninteresting once the window's summary is in hand)
            pass
        time.sleep(0.05)  # let pending handler tasks observe the close
        aloop.call_soon_threadsafe(aloop.stop)
        t.join(timeout=10.0)
        if side_err:
            summary["injection_error"] = side_err[0]
        return summary, path

    def fresh_pool(plan=None):
        return ReplicaPool(engine, replicas=2, faults=plan)

    # ---------------- baseline: the no-fault steady window -----------------
    # best-of-3 like the recovery windows (and every committed dryrun on
    # this harness): the gate must compare uncontended capability on both
    # sides, not whichever window the 2-core host happened to squeeze
    def _p99(s):
        return ((s["latency_ms"] or {}).get("p99_ms")) or float("inf")

    # selection is by TAIL latency on both sides (goodput is offered-rate-
    # bound in these open-loop windows, ~identical across trials; p99 is the
    # contended-host-noise victim, so each side's best tail approximates its
    # uncontended capability — symmetric, like the other committed dryruns)
    pool = fresh_pool().start()
    base_summary = base_path = None
    for trial in range(3):
        s, p = serve_window(pool, f"baseline_t{trial}" if trial else "baseline")
        if base_summary is None or _p99(s) < _p99(base_summary):
            base_summary, base_path = s, p
    pool.stop()
    print(json.dumps({
        "baseline": {
            "completed": base_summary["completed"],
            "slo": base_summary["slo"],
            "stranded": base_summary["stranded_futures"],
        }
    }), flush=True)

    # ---------------- per-class injections ---------------------------------
    def inject_socket_garbage(port):
        with socket.create_connection(("127.0.0.1", port), timeout=10.0) as sk:
            sk.settimeout(10.0)
            sk.sendall(b"NOT JSON {{{\n")
            rep = json.loads(sk.makefile("rb").readline())
            assert rep == {"ok": False, "reason": "bad_json"}, rep

    def inject_partial_line(port):
        sk = socket.create_connection(("127.0.0.1", port), timeout=10.0)
        sk.sendall(b'{"id": "frag", "x": [[')  # died mid-write
        sk.close()

    def inject_socket_drop(port):
        sk = socket.create_connection(("127.0.0.1", port), timeout=10.0)
        sk.sendall(
            (json.dumps({"id": "dropper", "x": samples["x"][0].tolist()}) + "\n").encode()
        )
        sk.close()  # vanished before the reply

    def inject_stalled_client(port):
        # connect, send NOTHING: the server must reap the slot at
        # conn_timeout_s with the typed idle_timeout reply + close
        with socket.create_connection(("127.0.0.1", port), timeout=10.0) as sk:
            sk.settimeout(cfg.serve.conn_timeout_s + 10.0)
            fh = sk.makefile("rb")
            rep = json.loads(fh.readline())
            assert rep == {"ok": False, "reason": "idle_timeout"}, rep
            assert fh.readline() == b""

    def inject_corrupt_swap(port):
        with ServeClient("127.0.0.1", port, timeout_s=30.0) as client:
            rep = client.swap(tags={"hdce": "hdce_bad"})
            assert rep["ok"] is False and "swap_failed" in rep["reason"], rep
            # the old params kept serving; a GOOD tagged swap then lands with
            # a zero compile delta (the PR-7 pin, now under chaos)
            rep = client.swap(tags={"hdce": "hdce_last", "sc": "sc_last"})
            assert rep["ok"] is True, rep
            assert all(v == 0 for v in rep["swap"]["compile"].values()), rep

    def inject_autotune_corrupt(port):
        # mid-run table corruption: the warmed engine never re-reads it (no
        # effect on live serving) and the dispatcher degrades instead of
        # raising on the next read
        scratch_table = batching_autotune.table_path()
        with open(scratch_table, "w") as fh:
            fh.write("{ corrupt json")
        # invalidate() clears the installed path too — re-pin the scratch
        # table so the degraded read (and any re-tune) never touches the
        # COMMITTED results/autotune table
        batching_autotune.invalidate_cache()
        batching_autotune.set_table_path(scratch_table)
        assert batching_autotune.load_table() == {}, batching_autotune.table_path()
        assert batching_autotune.table_status() == "corrupt"
        assert batching_autotune.lookup(int(cfg.serve.max_batch)) is None

    injections = {
        "socket_garbage": inject_socket_garbage,
        "partial_line": inject_partial_line,
        "socket_drop": inject_socket_drop,
        "stalled_client": inject_stalled_client,
        "corrupt_swap": inject_corrupt_swap,
        "autotune_corrupt": inject_autotune_corrupt,
    }
    worker_plans = {
        "replica_crash": lambda: FaultPlan(
            [FaultSpec("replica_crash", at=2, replica="serve-replica-1")], seed=seed
        ),
        "worker_exception": lambda: FaultPlan(
            [FaultSpec("worker_exception", at=2)], seed=seed
        ),
    }

    headline: dict = {
        "devices": devices, "n": n, "rate": rate, "deadline_ms": deadline_ms,
        "report_threshold_pct": float(threshold),
        "note": (
            "virtual-2-core wiring proof: behavior gates (stranded futures, "
            "SLO re-attainment within 0.05 absolute, breaker fraction, "
            "compile delta) are absolute/invariant; the %-threshold latency "
            "rows compare identical code across windows where host tail "
            "noise dominates — interleaved best-of-3 by p99 per side, 50% "
            "threshold (re-run on real hardware arms the default 10%)"
        ),
        "seed": seed, "buckets": list(cfg.serve.buckets),
        "batching_race": warm["batching"]["mode"],
        "breaker": {"high_frac": cfg.serve.breaker_high_frac,
                    "low_frac": cfg.serve.breaker_low_frac},
        "supervision": {"interval_s": cfg.serve.supervise_interval_s,
                        "backoff_s": cfg.serve.restart_backoff_s,
                        "budget": cfg.serve.restart_budget},
        "baseline": {"path": base_path, "slo": base_summary["slo"],
                     "completed": base_summary["completed"]},
        "classes": {},
    }
    if only_classes:
        headline["classes_filter"] = run_classes
    all_pass = True
    for kind in run_classes:
        plan = worker_plans[kind]() if kind in worker_plans else FaultPlan(seed=seed)
        pool = fresh_pool(plan).start()
        fault_summary, _fault_path = serve_window(
            pool, f"{kind}_fault", during=injections.get(kind)
        )
        # recovery on the SAME pool: the restarted/survivor replicas must
        # re-attain the SLO with zero new compiles. INTERLEAVED best-of
        # trials against a CONTEMPORANEOUS no-fault baseline pool, like
        # every committed dryrun on this 2-core harness: recovery BEHAVIOR
        # (stranded/give-ups/SLO) must hold on every trial, but the
        # %-threshold latency rows compare identical code, where host load
        # drifts across the minutes this matrix runs — adjacent windows are
        # the only honest comparison.
        rec_summary = rec_path = None
        lb_summary = lb_path = None
        rec_trials = []
        for trial in range(3):
            s, p = serve_window(pool, f"{kind}_recovery_t{trial}")
            rec_trials.append({
                "trial": trial, "goodput_rps": s["goodput_rps"],
                "p99_ms": (s["latency_ms"] or {}).get("p99_ms"),
                "stranded_futures": s["stranded_futures"],
                "give_ups": s["give_ups"],
                "hard_give_ups": s["give_ups"] - s["deadline_give_ups"],
                "slo": s["slo"],
            })
            if rec_summary is None or _p99(s) < _p99(rec_summary):
                rec_summary, rec_path = s, p
            bpool = fresh_pool().start()
            sb, pb = serve_window(bpool, f"{kind}_base_t{trial}")
            bpool.stop()
            if lb_summary is None or _p99(sb) < _p99(lb_summary):
                lb_summary, lb_path = sb, pb
        health = pool.health()
        pool.stop()
        report_md = os.path.join(out_dir, f"report_{kind}.md")
        rc = report_main(
            [f"--current={rec_path}", f"--baseline={lb_path}",
             f"--threshold={threshold}", f"--out={report_md}"]
        )
        checks = {
            "stranded_futures_fault": fault_summary["stranded_futures"],
            # behavior must hold on EVERY recovery trial (only the latency
            # gate reads the best-goodput one)
            "stranded_futures_recovery": max(
                t["stranded_futures"] for t in rec_trials
            ),
            "give_ups_fault": fault_summary["give_ups"],
            "give_ups_recovery": max(t["give_ups"] for t in rec_trials),
            # retries exhausted against a live server — the alarming kind
            # (deadline-exhausted give-ups are typed SLO misses, gated by
            # the report's attainment row instead)
            "hard_give_ups_recovery": max(t["hard_give_ups"] for t in rec_trials),
            "recovery_trials": rec_trials,
            "reconnects_fault": fault_summary["reconnects"],
            "retries_fault": fault_summary["retries"],
            "fired": list(plan.fired),
            "restarts": health["restarts"],
            "quarantined": health["quarantined"],
            "slo_fault": fault_summary["slo"],
            "slo_recovery": rec_summary["slo"],
            "slo_local_baseline": lb_summary["slo"],
            "injection_error": fault_summary.get("injection_error"),
            "report_exit": rc,
        }
        # SLO re-attainment, checked ABSOLUTELY here (never diluted by the
        # report threshold): the recovered pool must attain within 0.05 of
        # its contemporaneous no-fault baseline
        rec_att = (rec_summary["slo"] or {}).get("attainment")
        lb_att = (lb_summary["slo"] or {}).get("attainment")
        slo_ok = rec_att is not None and (lb_att is None or rec_att >= lb_att - 0.05)
        checks["slo_reattained"] = slo_ok
        expected_fire = kind in worker_plans
        ok = (
            checks["stranded_futures_fault"] == 0
            and checks["stranded_futures_recovery"] == 0
            and checks["hard_give_ups_recovery"] == 0
            and checks["injection_error"] is None
            and slo_ok
            and rc == 0
            and (not expected_fire or (plan.fired and health["restarts"] >= 1))
            and not health["quarantined"]
        )
        checks["ok"] = ok
        all_pass = all_pass and ok
        headline["classes"][kind] = checks
        print(json.dumps({kind: {k: checks[k] for k in (
            "ok", "report_exit", "restarts", "stranded_futures_fault",
            "stranded_futures_recovery", "reconnects_fault")}}), flush=True)

    # the cumulative request-path compile gate across EVERY chaos window:
    # eight fault classes, two traffic windows each, restarts, swaps — and
    # not one compile after warmup
    compile_delta = engine.request_path_compiles()
    headline["compile_delta_after_all_classes"] = compile_delta
    all_pass = all_pass and all(v == 0 for v in compile_delta.values())
    # the runtime lock-order witness: with QDML_LOCKDEP=1 every lock in the
    # stack recorded its acquisition edges across injected crashes,
    # restarts, and swaps — zero inversions is part of the headline gate
    # (disabled runs record the block too, with enabled=false, so the
    # committed artifact documents which mode produced it)
    witness = lockdep.witness_summary()
    headline["lockdep"] = witness
    if witness["enabled"]:
        all_pass = all_pass and witness["inversions"] == 0
    headline["all_pass"] = all_pass
    batching_autotune.set_table_path(None)
    with open(os.path.join(out_dir, "CHAOS_DRYRUN.json"), "w") as fh:
        json.dump(headline, fh, indent=2)
    print(json.dumps({
        "all_pass": all_pass, "compile_delta": compile_delta,
        "lockdep": {k: witness[k] for k in
                    ("enabled", "locks", "edges", "inversions")},
    }))
    return 0 if all_pass else 1


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
