"""On-chip perf session, round 4: device-time decompositions.

Run on the real TPU when the tunnel is up:
    python scripts/r4_perf_session.py [out.json]

Round 3 left two claims resting on WALL-time measurements that the tunnelled
single-chip backend contaminates with a ~1.5 ms/dispatch host gap
(docs/ROOFLINE.md). This session separates device-busy time from wall time by
parsing ``jax.profiler`` traces (the same extraction ROOFLINE.md did by hand
for the r3 HDCE step), and answers:

1. **Conv-width scaling probe** (VERDICT r4 ask #1): HDCE bf16 step at trunk
   features 32 / 64 / 128 — wall sps, wall MFU AND device-busy MFU per
   width. If device-busy MFU rises materially with width, the roofline's
   "32-channel lane occupancy caps the step" claim is confirmed; if flat,
   the ceiling lives elsewhere.
2. **Generator device cost** (ask #1): device-busy ms/step of the scan-fused
   path minus the fixed-batch step isolates the in-scan generator; measured
   for the threefry vs hardware-RBG streams (~5.5 M normal draws/step,
   dominated by the 2x1024/sample label noise). Top per-op durations inside
   the scan module are recorded so the tail has names.
3. **Pallas story reconciliation** (ask #2): QSC circuit forward AND
   backward, dense vs whole-circuit pallas kernel — wall time (the r3
   microbench's only metric) next to device-busy time per call, plus the
   full-step alternating A/B. The r3 contradiction (kernel forward 2.5x
   slower at 2069 us wall yet the step wins 4/4 A/B) is decided by whether
   the forward gap survives in device time.
"""

from __future__ import annotations

import collections
import glob
import gzip
import json
import os
import shutil
import statistics
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from qdml_tpu.utils.compile_cache import enable_compile_cache

enable_compile_cache()

import jax
import jax.numpy as jnp
import numpy as np

import bench

# Round-4 asks, re-armed for round 5: QDML_PERF_OUT_DIR redirects the whole
# artifact set (traces + json) without touching the probe code.
OUT_DIR = os.environ.get("QDML_PERF_OUT_DIR", "results/perf_r4")


# ---------------------------------------------------------------------------
# Trace-based device-busy extraction
# ---------------------------------------------------------------------------


def _load_trace_events(trace_dir: str) -> list:
    paths = glob.glob(
        os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True
    )
    newest = max(paths, key=os.path.getmtime)
    with gzip.open(newest) as fh:
        return json.load(fh)["traceEvents"]


def _device_tids(ev: list, thread: str) -> set:
    dev_pids = {
        e["pid"]
        for e in ev
        if e.get("ph") == "M"
        and e.get("name") == "process_name"
        and "device" in str(e.get("args", {}).get("name", "")).lower()
    }
    return {
        (e["pid"], e["tid"])
        for e in ev
        if e.get("ph") == "M"
        and e.get("name") == "thread_name"
        and e.get("args", {}).get("name") == thread
        and e["pid"] in dev_pids
    }


def device_busy_profile(fn, reps: int, keep_trace: str | None = None) -> dict:
    """Trace ``reps`` calls of ``fn`` (which must force completion itself via
    a host transfer) and return device-busy stats from the XLA Modules
    timeline: total busy ms per call + the top ops by accumulated duration.

    ``keep_trace``: optional path to copy the raw .trace.json.gz to (committed
    evidence)."""
    fn()  # warmup/compile outside the trace
    tmp = tempfile.mkdtemp(prefix="r4trace_")
    try:
        with jax.profiler.trace(tmp):
            for _ in range(reps):
                fn()
        ev = _load_trace_events(tmp)
        if keep_trace:
            src = max(
                glob.glob(os.path.join(tmp, "**", "*.trace.json.gz"), recursive=True),
                key=os.path.getmtime,
            )
            os.makedirs(os.path.dirname(keep_trace), exist_ok=True)
            shutil.copy(src, keep_trace)
    finally:
        if not keep_trace:
            shutil.rmtree(tmp, ignore_errors=True)
    mod_tids = _device_tids(ev, "XLA Modules")
    op_tids = _device_tids(ev, "XLA Ops")
    busy_us = sum(
        e.get("dur", 0)
        for e in ev
        if e.get("ph") == "X" and (e.get("pid"), e.get("tid")) in mod_tids
    )
    ops = collections.Counter()
    for e in ev:
        if e.get("ph") == "X" and (e.get("pid"), e.get("tid")) in op_tids:
            ops[e["name"]] += e.get("dur", 0)
    top = [
        {"op": k, "total_us": round(v, 1), "per_call_us": round(v / reps, 1)}
        for k, v in ops.most_common(12)
    ]
    return {
        "device_busy_ms_per_call": round(busy_us / 1e3 / reps, 3),
        "reps": reps,
        "top_ops": top,
    }


def _save(out: dict, out_path: str) -> None:
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w") as fh:
        json.dump(out, fh, indent=1)


def _guard(out: dict, key: str, fn) -> None:
    # Resume support: a probe that already succeeded in an earlier (tunnel-
    # interrupted) invocation is kept, so a session re-fire goes straight to
    # the missing probes instead of re-measuring — short tunnel windows are
    # the scarce resource (round-5: a 7-minute window closed mid-session).
    prior = out.get(key)
    if isinstance(prior, dict) and "error" not in prior:
        print(key, "already measured — skipping", flush=True)
        return
    try:
        out[key] = fn()
    except Exception as e:  # noqa: BLE001
        out[key] = {"error": f"{type(e).__name__}: {e}"}
    print(key, json.dumps(out[key])[:400], flush=True)


# ---------------------------------------------------------------------------
# 1. Conv-width scaling probe
# ---------------------------------------------------------------------------


def width_probe(features: int, trace_path: str | None) -> dict:
    from qdml_tpu.config import DataConfig, ExperimentConfig, ModelConfig, TrainConfig
    from qdml_tpu.train.hdce import init_hdce_state, make_hdce_train_step

    # wall measurement through the shared bench harness (same program)
    wall = bench._bench_hdce("bfloat16", 50, 45.0, features=features)

    cfg = ExperimentConfig(
        data=DataConfig(),
        model=ModelConfig(dtype="bfloat16", features=features),
        train=TrainConfig(batch_size=bench._CELL_BS, n_epochs=1),
    )
    batch = bench._make_grid_batch(cfg)
    batch = {k: batch[k] for k in ("yp_img", "h_label", "h_perf")}
    model, state = init_hdce_state(cfg, steps_per_epoch=100)
    step = make_hdce_train_step(model, state.tx)
    holder = {"state": state}

    def once():
        holder["state"], m = step(holder["state"], batch)
        float(m["loss"])

    prof = device_busy_profile(once, reps=10, keep_trace=trace_path)
    n_samples = 9 * bench._CELL_BS
    step_flops = 3.0 * bench.hdce_fwd_flops_per_sample(cfg) * n_samples
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")
    peak = bench._PEAK_BF16.get(gen, bench._PEAK_BF16["v5e"])
    busy_s = prof["device_busy_ms_per_call"] / 1e3
    return {
        "features": features,
        "wall_sps": wall["samples_per_sec"],
        "wall_mfu": round(wall["model_tflops"] * 1e12 / peak, 4),
        "device_busy_ms": prof["device_busy_ms_per_call"],
        "device_busy_mfu": round(step_flops / busy_s / peak, 4) if busy_s else None,
        "step_gflops": round(step_flops / 1e9, 2),
        "top_ops": prof["top_ops"][:6],
    }


# ---------------------------------------------------------------------------
# 2. Generator device cost (scan minus fixed-batch)
# ---------------------------------------------------------------------------


def scan_probe(rng_impl: str, keep_trace: str | None = None) -> dict:
    from qdml_tpu.config import DataConfig, ExperimentConfig, ModelConfig, TrainConfig
    from qdml_tpu.data.channels import ChannelGeometry
    from qdml_tpu.train.hdce import init_hdce_state, make_hdce_scan_steps

    k = 16
    wall = bench._bench_hdce_scan("bfloat16", k, 50, 45.0, rng_impl=rng_impl)

    cfg = ExperimentConfig(
        data=DataConfig(rng_impl=rng_impl),
        model=ModelConfig(dtype="bfloat16"),
        train=TrainConfig(batch_size=bench._CELL_BS, n_epochs=1),
    )
    geom = ChannelGeometry.from_config(cfg.data)
    s, u = bench._GRID
    scen, user, idx1 = bench._grid_coords()
    idx = jnp.broadcast_to(idx1[None], (k, s, u, bench._CELL_BS)).astype(jnp.int32)
    snrs = jnp.full((k,), float(cfg.data.snr_db), jnp.float32)
    model, state = init_hdce_state(cfg, steps_per_epoch=100)
    run = make_hdce_scan_steps(model, geom)
    seed = jnp.uint32(0)
    holder = {"state": state}

    def once():
        holder["state"], ms = run(holder["state"], seed, scen, user, idx, snrs)
        float(ms["loss"][-1])

    prof = device_busy_profile(once, reps=4, keep_trace=keep_trace)
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")
    peak = bench._PEAK_BF16.get(gen, bench._PEAK_BF16["v5e"])
    busy_per_step = prof["device_busy_ms_per_call"] / k
    step_flops = 3.0 * bench.hdce_fwd_flops_per_sample(cfg) * 9 * bench._CELL_BS
    return {
        "rng_impl": rng_impl,
        "wall_sps": wall["samples_per_sec"],
        "wall_mfu": round(wall["model_tflops"] * 1e12 / peak, 4),
        "device_busy_ms_per_step": round(busy_per_step, 3),
        # busy == 0 when the trace has no device timeline (non-TPU smoke runs)
        "device_busy_mfu": (
            round(step_flops / (busy_per_step / 1e3) / peak, 4) if busy_per_step else None
        ),
        "top_ops": prof["top_ops"],
    }


# ---------------------------------------------------------------------------
# 3. QSC circuit forward/backward device decomposition
# ---------------------------------------------------------------------------


def qsc_circuit_probe(backend: str) -> dict:
    from qdml_tpu.quantum.circuits import run_circuit

    B, N, L = 2304, 6, 3
    rng = np.random.default_rng(0)
    angles = jnp.asarray(rng.uniform(-1, 1, (B, N)).astype(np.float32))
    w = jnp.asarray(rng.uniform(-3, 3, (L, N, 2)).astype(np.float32))

    fwd = jax.jit(lambda a, ww: run_circuit(a, ww, N, L, backend))
    bwd = jax.jit(
        jax.grad(lambda a, ww: jnp.sum(run_circuit(a, ww, N, L, backend) ** 2), (0, 1))
    )

    def wall(fn, *args, reps=50):
        out = fn(*args)
        float(jnp.sum(jax.tree_util.tree_leaves(out)[0]))
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn(*args)
        float(jnp.sum(jax.tree_util.tree_leaves(out)[0]))
        return (time.perf_counter() - t0) / reps * 1e6

    res = {"backend": backend}
    res["fwd_wall_us"] = round(wall(fwd, angles, w), 1)
    res["fwd_device"] = device_busy_profile(
        lambda: float(jnp.sum(fwd(angles, w))), reps=30
    )
    res["bwd_wall_us"] = round(wall(bwd, angles, w), 1)
    res["bwd_device"] = device_busy_profile(
        lambda: float(jnp.sum(bwd(angles, w)[0])), reps=30
    )
    return res


# ---------------------------------------------------------------------------
# 4. QSC full-step alternating A/B (r3 machinery)
# ---------------------------------------------------------------------------


def qsc_step_ab(rounds: int = 6) -> dict:
    results: dict = {"dense": [], "pallas": []}
    for r in range(rounds):
        for k in ("dense", "pallas"):
            try:
                results[k].append(bench._bench_qsc(k, 50, 25.0)["samples_per_sec"])
            except Exception as e:  # noqa: BLE001
                results[k].append(None)
                results.setdefault("errors", []).append(f"{k}@{r}: {e}")
        print(f"[qsc_ab] round {r}: {results['dense'][-1]} vs {results['pallas'][-1]}", flush=True)
    out = {"rounds": results}
    for k in ("dense", "pallas"):
        vals = [v for v in results[k] if v is not None]
        if vals:
            out[f"{k}_med"] = round(statistics.median(vals), 1)
    out["pallas_wins"] = sum(
        1
        for d, p in zip(results["dense"], results["pallas"])
        if d is not None and p is not None and p > d
    )
    return out


def main() -> None:
    out_path = sys.argv[1] if len(sys.argv) > 1 else f"{OUT_DIR}/r4_perf_session.json"
    print("backend:", jax.default_backend(), flush=True)
    out: dict = {}
    if os.path.exists(out_path):
        try:
            with open(out_path) as fh:
                out = json.load(fh)
            print("resuming from", out_path, "keys:", sorted(out), flush=True)
        except Exception:  # noqa: BLE001
            out = {}
    out["backend"] = jax.default_backend()
    if out["backend"] != "tpu":
        print("WARNING: not on TPU — numbers will not be committed evidence", flush=True)

    for feats in (32, 64, 128):
        trace = f"{OUT_DIR}/hdce_w{feats}.trace.json.gz" if feats in (32, 128) else None
        _guard(out, f"width_{feats}", lambda f=feats, t=trace: width_probe(f, t))
        _save(out, out_path)

    for impl in ("threefry", "rbg"):
        trace = f"{OUT_DIR}/scan_{impl}.trace.json.gz"
        _guard(out, f"scan_{impl}", lambda i=impl, t=trace: scan_probe(i, t))
        _save(out, out_path)

    for backend in ("dense", "pallas"):
        _guard(out, f"qsc_fwd_bwd_{backend}", lambda b=backend: qsc_circuit_probe(b))
        _save(out, out_path)

    _guard(out, "qsc_step_ab", qsc_step_ab)
    _save(out, out_path)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
