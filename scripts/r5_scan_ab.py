"""Alternating A/B: default scan path vs the generator-tail levers (round 5).

The bench headline is the fixed default-stream scan measurement
(``hdce_bf16_scan``); promoting a faster variant requires a committed
alternating A/B, not a per-run max of noisy single captures (bench.py
headline-policy comment). This session measures, interleaved per round so
tunnel-window drift cancels:

  A. ``default``  — threefry bits, direct trig          (current headline)
  B. ``fast``     — hardware-RBG bits, angle-split trig (algorithm-equivalent)
  C. ``fast_b16m``— B + bfloat16 Adam moments           (documented deviation)

Usage:  python scripts/r5_scan_ab.py [out.json] [rounds]
"""

from __future__ import annotations

import json
import os
import statistics
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from qdml_tpu.utils.compile_cache import enable_compile_cache

enable_compile_cache()

import jax

import bench

VARIANTS = {
    "default": dict(rng_impl="threefry", trig_impl="direct"),
    "fast": dict(rng_impl="rbg", trig_impl="split"),
    "fast_b16m": dict(rng_impl="rbg", trig_impl="split", moments_dtype="bfloat16"),
}


def main() -> None:
    out_path = sys.argv[1] if len(sys.argv) > 1 else "results/perf_r5/scan_ab.json"
    rounds = int(sys.argv[2]) if len(sys.argv) > 2 else 5
    print("backend:", jax.default_backend(), flush=True)
    results: dict = {"backend": jax.default_backend(), "rounds": {k: [] for k in VARIANTS}}
    for r in range(rounds):
        for name, kw in VARIANTS.items():
            try:
                sps = bench._bench_hdce_scan("bfloat16", 16, 40, 30.0, **kw)[
                    "samples_per_sec"
                ]
            except Exception as e:  # noqa: BLE001
                sps = None
                results.setdefault("errors", []).append(f"{name}@{r}: {e}")
            results["rounds"][name].append(sps)
        print(
            f"[scan_ab] round {r}: "
            + " vs ".join(f"{k}={results['rounds'][k][-1]}" for k in VARIANTS),
            flush=True,
        )
    for name in VARIANTS:
        vals = [v for v in results["rounds"][name] if v is not None]
        if vals:
            results[f"{name}_med"] = round(statistics.median(vals), 1)
    # Only claim a fast_wins verdict when at least one default/fast pair
    # actually measured (ADVICE r5 low): an all-errored session used to emit
    # '"fast_wins": 0', which the session/watcher grep gates read as the
    # phase completing with evidence.
    valid_pairs = [
        (d, f)
        for d, f in zip(results["rounds"]["default"], results["rounds"]["fast"])
        if d is not None and f is not None
    ]
    if valid_pairs:
        results["fast_wins"] = sum(1 for d, f in valid_pairs if f > d)
        results["n_pairs"] = len(valid_pairs)
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w") as fh:
        json.dump(results, fh, indent=1)
    print(json.dumps(results), flush=True)


if __name__ == "__main__":
    main()
