#!/bin/bash
# Seed spread for the hardware-noise robustness study: repeat the
# plain-vs-QuantumNAT comparison (scripts/r3_noise_robustness.sh protocol)
# at 2 more training seeds. Eval keeps the COMMON seed-2026 test stream so
# across-seed differences measure training variance (same discipline as
# scripts/r3_multiseed.sh).
set -e
cd /root/repo
mkdir -p runs
for s in 2 3; do
  SEEDS="--train.seed=$s --data.seed=$((2026 + s))"
  python -m qdml_tpu.cli train-qsc $SEEDS --train.n_epochs=30 --train.resume=true \
      --train.workdir=runs/nr_plain_s$s > runs/nr_plain_s$s.log 2>&1
  python -m qdml_tpu.cli train-qsc $SEEDS --quantum.use_quantumnat=true --train.n_epochs=30 \
      --train.resume=true --train.workdir=runs/nr_nat_s$s > runs/nr_nat_s$s.log 2>&1
  python scripts/r3_noise_robustness.py runs/nr_plain_s$s/Pn_128/default \
      runs/nr_nat_s$s/Pn_128/default results/noise_robustness/seed$s
done
echo "NOISE ROBUSTNESS SEEDS DONE"
