#!/bin/bash
# On-chip-QNN gradient pruning (reference C8, Estimators...py:205-228 —
# shipped OFF, never measured): train the QSC with magnitude pruning of
# gradients (threshold 0.1, the reference default) under the same 30-epoch
# protocol as the robustness study, and evaluate it on the same common
# stream + noise grid. Quantifies what the reference's dormant feature
# actually costs/buys.
set -e
cd /root/repo
mkdir -p runs
python -m qdml_tpu.cli train-qsc --quantum.use_gradient_pruning=true \
    --train.n_epochs=30 --train.resume=true \
    --train.workdir=runs/nr_prune > runs/nr_prune.log 2>&1
# reuse the study evaluator: "plain" slot = pruned model, "nat" slot = the
# seed-1 NAT model for side-by-side context
python scripts/r3_noise_robustness.py runs/nr_prune/Pn_128/default \
    runs/nr_nat/Pn_128/default results/noise_robustness/grad_prune \
    grad_prune quantumnat
echo "GRAD PRUNE RUN DONE"
