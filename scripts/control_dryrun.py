"""Closed-loop fleet-control dryrun on virtual devices (ISSUE 10).

The control-plane twin of serve_fleet_dryrun: force a multi-device CPU
backend, train a small HDCE + scenario classifier, serve them, inject
channel-family drift into the offered traffic mid-run, and let the
:class:`~qdml_tpu.control.loop.FleetController` close the loop — detector
fires, ONLY the drifted trunk fine-tunes, the canary gates the candidate,
the explicit-tag hot-swap deploys it with zero request-path compiles, and
the served NMSE on the (still drifted) traffic recovers to pre-drift
levels. Writes ``results/control_dryrun/``:

- ``loadgen_baseline_t{N}.jsonl`` — phase A: stationary traffic on the
  original checkpoint, the pre-drift reference (interleaved best-of-N
  trials, one fresh engine each — per-phase NMSE is deterministic, only
  the 2-core host's timing needs the best-of, same as serve_fleet_dryrun);
- ``loadgen_drift.jsonl``  — phase B: ``--drift-at`` traffic against an
  external pool the controller is polling live; carries the ``drift_event``
  + ``control_event`` records of detection and adaptation;
- ``loadgen_recovered_t{N}.jsonl`` — phase C: all-drifted traffic on a
  fresh engine restarted onto the PROMOTED tag
  (``from_workdir(tags={"hdce": "hdce_last"})``), interleaved with phase A;
- ``CONTROL_DRYRUN.json`` — the headline: per-phase NMSE on the drifting
  family, the detection/finetune/canary/swap records, the zero-compile
  gates, and the report-gate exit code;
- ``report_control.md`` — ``qdml-tpu report`` over recovered-vs-baseline
  (exit 0 = the loop healed the fleet back to its committed reference).

Compile accounting: the controller fine-tunes IN PROCESS here (a real
fleet runs the trainer out-of-process), so serving-window compile gates are
measured per phase: phases A/C use the engine's post-warmup snapshot,
phase B the traffic-window counter delta, and the swap record carries its
own all-zero delta. Detection runs under live traffic (the controller
thread polls the pool during phase B in dry-run/report-only mode);
adaptation executes between phases for deterministic, uncontended phase
timings on a 2-core host.

Run: ``python scripts/control_dryrun.py [--devices=4] [--n=768] [--rate=80]``
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from qdml_tpu.utils.platform import force_cpu  # noqa: E402

DRIFT_SCENARIO = 0
DRIFT_STEP = 4


def main(argv: list[str]) -> int:
    devices = int(next((a.split("=", 1)[1] for a in argv if a.startswith("--devices=")), 4))
    n = int(next((a.split("=", 1)[1] for a in argv if a.startswith("--n=")), 768))
    rate = float(next((a.split("=", 1)[1] for a in argv if a.startswith("--rate=")), 80.0))
    force_cpu(devices)

    from qdml_tpu.config import (
        ControlConfig, DataConfig, ExperimentConfig, MeshConfig, ModelConfig,
        ServeConfig, TrainConfig,
    )
    from qdml_tpu.control.loop import FleetController, PoolPoller
    from qdml_tpu.parallel.mesh import serve_mesh
    from qdml_tpu.serve import ReplicaPool, ServeEngine, run_loadgen
    from qdml_tpu.telemetry import run_manifest
    from qdml_tpu.telemetry.report import report_main
    from qdml_tpu.train.hdce import train_hdce
    from qdml_tpu.train.qsc import train_classifier
    from qdml_tpu.utils.compile_cache import compile_cache_stats
    from qdml_tpu.utils.metrics import MetricsLogger

    out_dir = os.path.join("results", "control_dryrun")
    os.makedirs(out_dir, exist_ok=True)
    workdir = os.path.join("workspace", "control_dryrun")

    cfg = ExperimentConfig(
        name="control_dryrun",
        data=DataConfig(n_ant=32, n_sub=16, n_beam=8, data_len=512),
        model=ModelConfig(features=16),
        train=TrainConfig(batch_size=32, n_epochs=6, probe_every=0),
        mesh=MeshConfig(data_axis=devices, model_axis=1, fed_axis=1),
        serve=ServeConfig(
            max_batch=32, buckets=(8, 16, 32), max_wait_ms=2.0, max_queue=512,
            drift_step=DRIFT_STEP, drift_scenario=DRIFT_SCENARIO,
            # the committed control baselines were measured under bucket
            # coalescing; a regenerated artifact must not silently flip
            # admission policy via the auto batching table
            batching="bucket",
        ),
        control=ControlConfig(
            ft_steps=300, ft_batch=32, probe_n=96,
            min_gain_db=0.3, tol_db=0.5, watch_ticks=2,
            autoscale=False,  # the drift loop is the story; scaling is pinned in tests
        ),
    )
    headline: dict = {
        "devices": devices, "n": n, "rate": rate,
        "drift": {"scenario": DRIFT_SCENARIO, "step": DRIFT_STEP},
        "workdir": workdir, "phases": {},
    }

    # -- train the fleet's models (once per dryrun; checkpoints land in the
    # workdir the serving engine and the controller share) -------------------
    if not os.path.isdir(os.path.join(workdir, "hdce_best")):
        log = MetricsLogger(os.path.join(out_dir, "train.log.jsonl"), echo=False,
                           manifest=run_manifest(cfg))
        try:
            train_hdce(cfg, logger=log, workdir=workdir)
            import dataclasses

            sc_cfg = dataclasses.replace(
                cfg, train=dataclasses.replace(cfg.train, n_epochs=10)
            )
            train_classifier(sc_cfg, quantum=False, logger=log, workdir=workdir)
        finally:
            log.close()
        os.remove(os.path.join(out_dir, "train.log.jsonl"))  # not an artifact

    mesh = serve_mesh(cfg)

    def fresh_engine(tags=None) -> ServeEngine:
        return ServeEngine.from_workdir(cfg, workdir, mesh=mesh, tags=tags)

    def run_phase(name, engine, path, drift_at=None, pool=None):
        logger = MetricsLogger(path, echo=False, manifest=run_manifest(cfg))
        try:
            summary = run_loadgen(
                cfg, engine, rate=rate, n=n, deadline_ms=2000.0, logger=logger,
                drift_at=drift_at, pool=pool,
            )
        finally:
            logger.close()
        print(f"[{name}] rps={summary['rps']} nmse_served={summary['nmse_db_served']} "
              f"compiles={summary['compile_cache_after_warmup']}")
        return summary

    # -- phase B: drift injected mid-run, controller watching live ----------
    drift_path = os.path.join(out_dir, "loadgen_drift.jsonl")
    logger_b = MetricsLogger(drift_path, echo=False, manifest=run_manifest(cfg))
    engine = fresh_engine()
    pool = ReplicaPool(engine, sink=logger_b.telemetry, log_requests=False).start()
    ctrl = FleetController(
        cfg, workdir, PoolPoller(pool, engine, workdir), engine=engine,
        sink=logger_b.telemetry, drift_step_hint=DRIFT_STEP,
    )
    # detection-only while traffic runs (report, don't act): adaptation is
    # executed between phases so the 2-core host's phase timings stay clean
    ctrl.dry_run = True
    thread, stop = ctrl.run_in_thread(interval_s=0.25)
    try:
        summary_b = run_loadgen(
            cfg, engine, rate=rate, n=n, deadline_ms=2000.0, logger=logger_b,
            drift_at=n // 2, pool=pool,
        )
    finally:
        stop.set()
        thread.join(timeout=10.0)
    print(f"[drift] windows pre={summary_b['windows']['pre_drift']['nmse_db_drift_scenario']} "
          f"post={summary_b['windows']['post_drift']['nmse_db_drift_scenario']} "
          f"live_detector_state={ctrl.monitor.state()}")

    # ground-truth windowed parity: replay phase B's chunked windows into the
    # controller's nmse_parity detector for the DRIFTING family (the
    # loadgen harness knows h_perf; a production fleet would feed labeled
    # shadow traffic here)
    parity_events = []
    for chunk in summary_b["windows"]["chunks"]:
        db = chunk.get("nmse_db_drift_scenario")
        if db is not None:
            ev = ctrl.observe_parity(DRIFT_SCENARIO, db)
            if ev:
                parity_events.append(ev)
    fired = ctrl.monitor.active()
    print(f"[detect] fired={fired} parity_events={parity_events}")
    if not any(s == DRIFT_SCENARIO for s, _ in fired):
        print("FATAL: drift was never detected"); return 1

    # -- adapt: finetune -> canary -> explicit-tag swap ----------------------
    ctrl.dry_run = False
    ctrl.deployer.dry_run = False
    pre_adapt_cache = compile_cache_stats()
    out = ctrl.tick()
    adapted = [e for e in out["events"] if e.get("action") == "adapted"]
    if not adapted:
        print("FATAL: adaptation did not complete:", out["events"]); return 1
    rec = adapted[0]
    assert rec["canary"]["passed"] is True
    assert rec["deploy"]["swap"]["compile"] == {"hits": 0, "misses": 0, "requests": 0}
    assert rec["deploy"]["swap"]["tags"]["hdce"] == "hdce_last"
    adapt_compiles = {
        k: v - pre_adapt_cache.get(k, 0) for k, v in compile_cache_stats().items()
    }
    headline["phases"]["drift"] = {
        "rps": summary_b["rps"],
        "windows": {k: summary_b["windows"][k] for k in ("pre_drift", "post_drift")},
        "compile_cache_traffic_window": summary_b["compile_cache_after_warmup"],
        "drift_events": {
            "live_confidence_streams": ctrl.monitor.state(),
            "parity": parity_events,
        },
    }
    headline["adaptation"] = {
        "finetune": rec["finetune"],
        "canary": rec["canary"],
        "swap": rec["deploy"]["swap"],
        "control_plane_compiles_during_adapt": adapt_compiles,
        "note": (
            "fine-tune + canary compile in the controller (control plane); "
            "the swap record's own counter delta is the request-path gate "
            "and is all-zero"
        ),
    }

    # phase B's pool retires before phase C opens a fresh one on the SAME
    # (now adapted) engine; the controller's later watch ticks poll the
    # stopped pool, which is defined behavior for the metrics view
    pool.stop()

    # -- phases A (baseline) + C (recovered): interleaved best-of-N ----------
    # Per-trial fresh engines: phase A restores the ORIGINAL checkpoint
    # (hdce_best via newest-tag resolution — the stale-best behavior the
    # deployer's explicit tags exist to bypass); phase C restarts onto the
    # PROMOTED tag (from_workdir's explicit-tag pin, the restart twin of the
    # swap fix; the phase-B engine already proved the LIVE swap above).
    # Interleaved trials, best-of per phase, exactly like serve_fleet_dryrun:
    # on a contended 2-core host per-run latency swings far past the report
    # gate's 10%, and blocked A-A-A-C-C-C ordering hands whichever phase ran
    # in the quiet window a fake win — NMSE per phase is deterministic (same
    # data, same params every trial); only the timing needs the best-of.
    trials = 3
    best: dict = {}
    trial_stats: dict = {"baseline": [], "recovered": []}
    for t in range(trials):
        for name, tags, drift_at in (
            ("baseline", None, None),
            ("recovered", {"hdce": "hdce_last"}, 0),
        ):
            path = os.path.join(out_dir, f"loadgen_{name}_t{t}.jsonl")
            summary = run_phase(
                f"{name} t{t}", fresh_engine(tags=tags), path, drift_at=drift_at
            )
            p50 = (summary["latency_ms"] or {}).get("p50_ms")
            trial_stats[name].append({"rps": summary["rps"], "p50_ms": p50})
            if name not in best or (p50 or 1e9) < (
                (best[name][0]["latency_ms"] or {}).get("p50_ms") or 1e9
            ):
                best[name] = (summary, path)
    sA, base_path = best["baseline"]
    summary_c, rec_path = best["recovered"]
    headline["phases"]["baseline"] = {
        "rps": sA["rps"], "nmse_db_served": sA["nmse_db_served"],
        "slo": sA["slo"], "trials": trial_stats["baseline"],
        "compile_cache_after_warmup": sA["compile_cache_after_warmup"],
    }

    # watch window: feed the recovered parity, confirm the deploy
    try:
        confirm = None
        for _ in range(cfg.control.watch_ticks + 1):
            ctrl.observe_parity(
                DRIFT_SCENARIO,
                summary_c["windows"]["post_drift"]["nmse_db_drift_scenario"],
            )
            out = ctrl.tick()
            confirm = next(
                (e for e in out["events"] if e.get("action") == "deploy_confirmed"),
                confirm,
            )
        if confirm is None:
            print("FATAL: deploy was not confirmed (rollback?)"); return 1
        print(f"[recovered] confirm={confirm}")
    finally:
        logger_b.close()

    pre_db = summary_b["windows"]["pre_drift"]["nmse_db_drift_scenario"]
    degraded_db = summary_b["windows"]["post_drift"]["nmse_db_drift_scenario"]
    recovered_db = summary_c["windows"]["post_drift"]["nmse_db_drift_scenario"]
    headline["phases"]["recovered"] = {
        "rps": summary_c["rps"], "nmse_db_served": summary_c["nmse_db_served"],
        "nmse_db_drift_scenario": recovered_db,
        "slo": summary_c["slo"], "trials": trial_stats["recovered"],
        "compile_cache_after_warmup": summary_c["compile_cache_after_warmup"],
        "watch_confirmed": confirm,
    }
    frac = (recovered_db - degraded_db) / (pre_db - degraded_db)
    headline["recovery"] = {
        "drift_family_nmse_db": {
            "pre_drift": pre_db, "degraded": degraded_db, "recovered": recovered_db,
        },
        "degradation_db": round(degraded_db - pre_db, 3),
        "recovered_vs_pre_drift_db": round(recovered_db - pre_db, 3),
        "fraction_of_degradation_recovered": round(frac, 3),
        # "recovered to pre-drift levels": back within half a dB of the
        # pre-drift window AND most of the degradation undone (phase windows
        # are different sample draws — ~0.3 dB of window noise is inherent;
        # the residual gap is the un-retrained classifier's misrouting tail,
        # see docs/CONTROL.md)
        "recovered_to_pre_drift_levels": bool(
            recovered_db <= pre_db + 0.5 and frac >= 0.6
        ),
    }

    # -- report round-trip: recovered vs baseline ----------------------------
    report_md = os.path.join(out_dir, "report_control.md")
    rc = report_main(
        [f"--current={rec_path}", f"--baseline={base_path}", f"--out={report_md}"]
    )
    headline["report_gate"] = {"exit_code": rc, "markdown": report_md}
    with open(os.path.join(out_dir, "CONTROL_DRYRUN.json"), "w") as fh:
        json.dump(headline, fh, indent=2)
    print(json.dumps(headline, indent=2))
    if rc != 0 or not headline["recovery"]["recovered_to_pre_drift_levels"]:
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
