#!/usr/bin/env python
"""On-chip scan-K sweep + QSC backend A/B re-run (round 3, second pass).

Captures, on the real TPU:

1. HDCE bf16 end-to-end training throughput (on-device generation inside the
   scan) at K in {1, 8, 16, 32} steps per dispatch — quantifies how the
   dispatch-gap amortization saturates and picks the best K for the bench
   headline.
2. A fresh 4x alternating pallas-vs-dense QSC A/B (the controlled comparison
   behind the README's kernel claim; single-shot wall numbers for this
   dispatch-bound step swing +-25%, see results/perf_r3/r3_qsc_ab.json for
   the original capture).

Writes results/perf_r3/r3_scan_sweep.json. Run from the repo root with the
TPU reachable:  python scripts/r3_scan_sweep.py
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from qdml_tpu.utils.compile_cache import enable_compile_cache

enable_compile_cache()

import jax

import bench as bench_mod

# Same generation-resolved peak the bench harness uses (bench.py main()).
_GEN = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")
_PEAK = bench_mod._PEAK_BF16.get(_GEN, bench_mod._PEAK_BF16["v5e"])


def scan_throughput(k: int) -> dict:
    """One K point, measured by the bench harness's own scan sub-bench so the
    sweep cannot drift from the driver-grade numbers."""
    d = bench_mod._bench_hdce_scan("bfloat16", k, max_steps=50, budget_s=30.0)
    d["k"] = k
    d["mfu"] = round(d["model_tflops"] * 1e12 / _PEAK, 4)
    return d


def qsc_ab(rounds: int = 4) -> list[dict]:
    out = []
    for r in range(rounds):
        row = {}
        for backend in ("dense", "pallas"):
            d = bench_mod._bench_qsc(backend, max_steps=50, budget_s=30.0)
            row[backend] = d["samples_per_sec"]
        row["pallas_wins"] = row["pallas"] > row["dense"]
        out.append(row)
        print(f"[ab] round {r}: {row}", flush=True)
    return out


def main() -> int:
    backend = jax.default_backend()
    if backend == "cpu":
        print("refusing to run the on-chip sweep on the CPU backend", file=sys.stderr)
        return 1
    record: dict = {"backend": backend, "devices": len(jax.devices())}
    record["tpu_gen"] = _GEN
    record["hdce_bf16_scan_sweep"] = [scan_throughput(k) for k in (1, 8, 16, 32)]
    for row in record["hdce_bf16_scan_sweep"]:
        print(f"[scan] K={row['k']}: {row['samples_per_sec']:,.0f} sps, "
              f"MFU {row['mfu']}", flush=True)
    record["qsc_ab"] = qsc_ab()
    wins = sum(r["pallas_wins"] for r in record["qsc_ab"])
    record["qsc_ab_pallas_wins"] = f"{wins}/{len(record['qsc_ab'])}"
    out = os.path.join("results", "perf_r3", "r3_scan_sweep.json")
    with open(out, "w") as fh:
        json.dump(record, fh, indent=1)
    print("wrote", out, flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
