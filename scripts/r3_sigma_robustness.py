"""Does LARGER QuantumNAT training noise buy state-level robustness?

The 3-seed study (results/noise_robustness/seed_spread.md) found no
seed-stable depolarizing-noise advantage at the reference's shipped
σ=0.01. This evaluates the full σ ensemble trained by the vmapped
noise-sweep trainer (``cli nat-sweep``: every member trained
simultaneously in ONE jitted step): each member (σ ∈ noise_sweep) is
extracted from the stacked ``nat_sweep_last`` checkpoint and scored on the
common test stream under the trajectory depolarizing grid.

MODEL-SELECTION CAVEAT (ADVICE r3): workdirs trained before round 4 only
have FINAL-EPOCH stacked params (``nat_sweep_last``) while the plain/NAT
seed studies score best-validation checkpoints (``qsc_best``); final-epoch
selection can depress ensemble clean accuracies, so for those artifacts
small cross-study clean deltas (≲2 pp, e.g. the σ=0.2/0.3 "clean cost"
onset) partially confound selection rule with σ. The round-4 trainer also
writes ``nat_sweep_member_best`` (every member's best-val params), which
this script PREFERS when present — aligning the selection rule with the
seed studies; the artifact records which source was used.

Usage: python scripts/r3_sigma_robustness.py [sweep_workdir out_dir]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from qdml_tpu.utils.platform import honor_platform_env

honor_platform_env()

import jax
import jax.numpy as jnp

from qdml_tpu.config import ExperimentConfig
from qdml_tpu.data.channels import ChannelGeometry
from qdml_tpu.data.datasets import make_network_batch
from qdml_tpu.models.qsc import QSCP128
from qdml_tpu.train.checkpoint import reconcile_quantum_cfg, restore_checkpoint

# single eval-protocol + artifact-format definition shared with the
# plain-vs-NAT study
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from r3_noise_robustness import accuracy, write_results  # noqa: E402

P_GRID = (0.0, 0.03, 0.1, 0.2)
N_TRAJ = 32
TEST_N = 4608

def main() -> None:
    wd = sys.argv[1] if len(sys.argv) > 1 else "runs/nr_sweep/Pn_128/default"
    out_dir = sys.argv[2] if len(sys.argv) > 2 else "results/noise_robustness/sigma_sweep"

    from qdml_tpu.train.checkpoint import has_checkpoint

    selection = "member_best" if has_checkpoint(wd, "nat_sweep_member_best") else "last"
    stacked, meta = restore_checkpoint(
        wd, "nat_sweep_member_best" if selection == "member_best" else "nat_sweep_last"
    )
    sigmas = meta["noise_levels"]
    # Architecture facts come from the checkpoint via the standard
    # reconciliation (no-op for pre-round-3 checkpoints without the meta).
    cfg = reconcile_quantum_cfg(ExperimentConfig(), meta)
    geom = ChannelGeometry.from_config(cfg.data)
    start = cfg.data.data_len * 3
    i = jnp.arange(start, start + TEST_N)
    batch = make_network_batch(
        jnp.uint32(cfg.data.seed), i % 3, (i // 3) % 3, i,
        jnp.float32(cfg.data.snr_db), geom,
    )

    out = {"p_grid": list(P_GRID), "sigmas": sigmas, "n_trajectories": N_TRAJ,
           "test_n": TEST_N, "snr_db": cfg.data.snr_db,
           "param_selection": selection, "curves": {}}
    for m, sigma in enumerate(sigmas):
        vars_ = {"params": jax.tree.map(lambda x: x[m], stacked["params"])}
        accs = []
        for p in P_GRID:
            model = QSCP128(
                n_qubits=cfg.quantum.n_qubits,
                n_layers=cfg.quantum.n_layers,
                n_classes=cfg.quantum.n_classes,
                input_norm=cfg.quantum.input_norm,
                backend="tensor",
                depolarizing_p=float(p),
                n_trajectories=N_TRAJ,
            )
            accs.append(
                round(accuracy(model, vars_, batch, jax.random.PRNGKey(17)), 4)
            )
        out["curves"][f"sigma={sigma:g}"] = accs
        print(f"sigma={sigma:g}: {accs}", flush=True)

    print(write_results(out_dir, out, "training sigma"))

if __name__ == "__main__":
    main()
