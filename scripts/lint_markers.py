#!/usr/bin/env python
"""Slow-marker lint: every test over the wall-clock threshold in a sample run
must carry ``@pytest.mark.slow`` — or be explicitly grandfathered.

The tier-1 suite has a hard wall budget (ROADMAP.md: 870 s); tests that creep
past a few seconds each are how a suite silently eats it. This linter closes
the loop: feed it a ``--durations=0`` report from a real run and it checks
that every offender either carries the ``slow`` marker (deselected from
tier-1) or appears in the committed allowlist with a reason.

The allowlist exists because "slow" is not the same as "optional": the
XLA-compile-dominated training e2e tests exceed any per-test threshold on the
1-core builder host yet ARE the tier-1 acceptance coverage — marking them
``slow`` would deselect the gate itself. New offenders outside that committed
set fail the lint, so unbudgeted slowness cannot land silently.

Usage:
    pytest tests/ -q -m 'not slow' --durations=0 > /tmp/durations.log
    python scripts/lint_markers.py --durations=/tmp/durations.log \
        [--threshold=5] [--allow=scripts/tier1_slow_allowlist.txt]
"""

from __future__ import annotations

import argparse
import ast
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# "12.34s call     tests/test_x.py::test_y[param]" — only the call phase
# counts (setup/teardown time belongs to fixtures, which the marker on the
# test cannot deselect on its own).
_DURATION_RE = re.compile(
    r"^\s*(?P<secs>\d+(?:\.\d+)?)s\s+call\s+(?P<nodeid>\S+)\s*$"
)


def parse_durations(text: str) -> dict[str, float]:
    """nodeid -> call seconds, max over parametrizations."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        m = _DURATION_RE.match(line)
        if not m:
            continue
        nodeid = m.group("nodeid").split("[", 1)[0]  # fold parametrizations
        secs = float(m.group("secs"))
        out[nodeid] = max(secs, out.get(nodeid, 0.0))
    return out


def _decorators_mark_slow(dec_list) -> bool:
    for dec in dec_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        # pytest.mark.slow -> Attribute(attr='slow', value=Attribute(attr='mark'))
        if isinstance(target, ast.Attribute) and target.attr == "slow":
            v = target.value
            if isinstance(v, ast.Attribute) and v.attr == "mark":
                return True
    return False


def has_slow_marker(path: str, test_name: str) -> bool:
    """True when the test function (or its class / module pytestmark) carries
    pytest.mark.slow. Source-level check: no pytest import, no collection."""
    try:
        tree = ast.parse(open(path).read())
    except (OSError, SyntaxError):
        return False

    def module_marked() -> bool:
        for node in tree.body:
            if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "pytestmark" for t in node.targets
            ):
                vals = (
                    node.value.elts if isinstance(node.value, (ast.List, ast.Tuple))
                    else [node.value]
                )
                if _decorators_mark_slow(vals):
                    return True
        return False

    def walk(body, inherited: bool) -> bool | None:
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node.name == test_name:
                    return inherited or _decorators_mark_slow(node.decorator_list)
            elif isinstance(node, ast.ClassDef):
                found = walk(
                    node.body, inherited or _decorators_mark_slow(node.decorator_list)
                )
                if found is not None:
                    return found
        return None

    found = walk(tree.body, module_marked())
    return bool(found)


def load_allowlist(path: str | None) -> set[str]:
    if not path or not os.path.exists(path):
        return set()
    out = set()
    for line in open(path):
        line = line.split("#", 1)[0].strip()
        if line:
            out.add(line)
    return out


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--durations", required=True, help="pytest --durations=0 output (file, or - for stdin)")
    ap.add_argument("--threshold", type=float, default=5.0, help="seconds of call wall-clock (default 5)")
    ap.add_argument(
        "--allow",
        default=os.path.join(REPO, "scripts", "tier1_slow_allowlist.txt"),
        help="grandfathered nodeids (one per line, # comments)",
    )
    args = ap.parse_args(argv)
    text = sys.stdin.read() if args.durations == "-" else open(args.durations).read()
    durations = parse_durations(text)
    if not durations:
        print("lint_markers: no '<secs>s call <nodeid>' lines found — run pytest with --durations=0")
        return 2
    allow = load_allowlist(args.allow)
    offenders = []
    for nodeid, secs in sorted(durations.items(), key=lambda kv: -kv[1]):
        if secs <= args.threshold:
            continue
        relpath, test_name = nodeid.split("::", 1)
        test_name = test_name.split("::")[-1]
        if has_slow_marker(os.path.join(REPO, relpath), test_name):
            continue
        if nodeid in allow:
            continue
        offenders.append((nodeid, secs))
    if offenders:
        print(
            f"lint_markers: {len(offenders)} test(s) over {args.threshold:g}s "
            "lack @pytest.mark.slow and are not in the allowlist:"
        )
        for nodeid, secs in offenders:
            print(f"  {secs:8.2f}s  {nodeid}")
        print(f"(mark them slow, or add to {args.allow} with a reason)")
        return 1
    n_over = sum(1 for s in durations.values() if s > args.threshold)
    print(
        f"lint_markers: OK — {len(durations)} timed tests, {n_over} over "
        f"{args.threshold:g}s, all marked slow or allowlisted"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
