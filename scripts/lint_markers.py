#!/usr/bin/env python
"""Slow-marker lint — thin shim over the graftlint engine.

The logic moved to :mod:`qdml_tpu.analysis.slowmarkers` (PR 4) so the repo
has ONE lint entry point: ``qdml-tpu lint --durations=FILE`` runs the same
check as part of the full static-analysis gate. This script keeps the
original standalone CLI (same flags, same exit codes) for existing callers
and docs.

Usage:
    pytest tests/ -q -m 'not slow' --durations=0 > /tmp/durations.log
    python scripts/lint_markers.py --durations=/tmp/durations.log \
        [--threshold=5] [--allow=scripts/tier1_slow_allowlist.txt]
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from qdml_tpu.analysis.slowmarkers import (  # noqa: E402
    DEFAULT_ALLOWLIST,
    check_durations,
    has_slow_marker,  # noqa: F401 — re-exported for the existing self-test
    load_allowlist,  # noqa: F401
    parse_durations,  # noqa: F401
)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--durations", required=True, help="pytest --durations=0 output (file, or - for stdin)")
    ap.add_argument("--threshold", type=float, default=5.0, help="seconds of call wall-clock (default 5)")
    ap.add_argument(
        "--allow",
        default=os.path.join(REPO, DEFAULT_ALLOWLIST),
        help="grandfathered nodeids (one per line, # comments)",
    )
    args = ap.parse_args(argv)
    text = sys.stdin.read() if args.durations == "-" else open(args.durations).read()
    findings = check_durations(
        REPO, text, threshold_s=args.threshold, allowlist_path=args.allow
    )
    empty_report = any(f.path == "(durations report)" for f in findings)
    if empty_report:
        print("lint_markers: no '<secs>s call <nodeid>' lines found — run pytest with --durations=0")
        return 2
    if findings:
        print(
            f"lint_markers: {len(findings)} test(s) over {args.threshold:g}s "
            "lack @pytest.mark.slow and are not in the allowlist:"
        )
        for f in findings:
            print(f"  {f.message}")
        print(f"(mark them slow, or add to {args.allow} with a reason)")
        return 1
    n = len(parse_durations(text))
    print(
        f"lint_markers: OK — {n} timed tests, all over-threshold ones "
        "marked slow or allowlisted"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
