#!/bin/bash
# Train the two classifiers for the hardware-noise robustness study
# (scripts/r3_noise_robustness.py): identical protocol except QuantumNAT.
# 30 epochs (the multiseed shortening rationale); CPU-feasible — the
# classifiers are small.
set -e
cd /root/repo
mkdir -p runs
python -m qdml_tpu.cli train-qsc --train.n_epochs=30 --train.resume=true \
    --train.workdir=runs/nr_plain > runs/nr_plain.log 2>&1
python -m qdml_tpu.cli train-qsc --quantum.use_quantumnat=true --train.n_epochs=30 \
    --train.resume=true --train.workdir=runs/nr_nat > runs/nr_nat.log 2>&1
python scripts/r3_noise_robustness.py
echo "NOISE ROBUSTNESS DONE"
