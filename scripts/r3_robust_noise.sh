#!/bin/bash
# Robust-preset QSC under state-level hardware noise
# (results/noise_robustness/robust_vs_nat/): train the input-norm +
# SNR-jitter classifier (robust_qsc preset, NO quantum-noise injection)
# under the study's common 30-epoch protocol, then evaluate it on the
# shared test stream + depolarizing grid side by side with the seed-1
# QuantumNAT model from scripts/r3_noise_robustness.sh.
set -e
cd /root/repo
mkdir -p runs
python -m qdml_tpu.cli train-qsc --preset=robust_qsc --train.n_epochs=30 \
    --train.resume=true --train.workdir=runs/nr_robust > runs/nr_robust.log 2>&1
python scripts/r3_noise_robustness.py runs/nr_robust/Pn_128/robust_qsc \
    runs/nr_nat/Pn_128/default results/noise_robustness/robust_vs_nat \
    robust quantumnat
echo "ROBUST NOISE DONE"
