#!/bin/bash
# Round-3 ablation (VERDICT r2 #5): settle the raw-pilot low-SNR question.
# Trains the two missing cells of the {input_norm} x {snr_jitter} grid at the
# full reference protocol (100 epochs), evals each, and leaves 4 comparable
# curves: raw (committed r2), norm-only, jitter-only, both (committed r2).
set -e
cd /root/repo
export JAX_PLATFORMS=cpu

for v in norm jitter; do
  if [ "$v" = norm ]; then OV="--quantum.input_norm=true"; else OV="--data.snr_jitter=5,15"; fi
  python -m qdml_tpu.cli train-qsc $OV --train.workdir=runs/ab_$v --train.resume=true \
      > runs/ab_$v.train.log 2>&1
  mkdir -p runs/ab_$v/Pn_128/default
  for t in hdce_best hdce_best.meta.json sc_best sc_best.meta.json; do
    cp -r runs/science/Pn_128/default/$t runs/ab_$v/Pn_128/default/ 2>/dev/null || true
  done
  python -m qdml_tpu.cli eval $OV --train.workdir=runs/ab_$v \
      --eval.results_dir=results/ablation/${v}_only > runs/ab_$v.eval.log 2>&1
done
echo "ABLATION DONE"
