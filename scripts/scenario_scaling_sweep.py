"""The S=3..64 scenario-scaling sweep on the virtual-device harness (ISSUE 9).

The scenario twin of ``scripts/qubit_scaling_sweep.py``: force an
8-virtual-device CPU backend (``utils.platform.force_cpu``), run ``bench.py``'s
``scenario_scaling`` child over the full grid (the routing dispatcher races
dense-all-trunks vs capacity-bucketed sparse at every S and the winner is
timed + costed per point), and round-trip the artifact through the
``qdml-tpu report`` gate. Writes ``results/scenario_scaling/``:

- ``scenario_scaling.jsonl`` — manifest-headed telemetry: the
  ``scenario_scaling`` record (per-S winner, candidate timings, capacity,
  XLA cost, roofline, sparse-vs-dense value agreement);
- ``routing_table.json`` — the selection table the sweep wrote: the committed
  PROOF of which dispatch the race picks per (S, batch) on this harness;
- ``report_scenario.md`` — the rendered report (per-S ``best_of_dispatch``
  gate rows + the scenario-scaling crossover section);
- ``SCENARIO_SCALING.json`` — the headline (S -> dispatch/rows-per-sec map,
  the dense-at-S=3 and sparse-at-S>=16 checks, the report exit code).

Run: ``python scripts/scenario_scaling_sweep.py [--devices=8] [--budget=1.0]``
(a few minutes on a CPU host — the S=64 dense race entrant is deliberately
~50x the sparse work). Virtual-device timings measure XLA:CPU execution, not
ICI scaling — the artifact is the wiring-and-dispatch proof (dense must keep
winning the reference's S=3, sparse must WIN the race from S=16 up, table ->
record -> report gate round-trip at exit 0); the TPU re-run is the hardware
headline.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from qdml_tpu.utils.platform import force_cpu  # noqa: E402


def main(argv: list[str]) -> int:
    devices = int(
        next((a.split("=", 1)[1] for a in argv if a.startswith("--devices=")), 8)
    )
    budget = next((a.split("=", 1)[1] for a in argv if a.startswith("--budget=")), None)
    force_cpu(devices)
    if budget is not None:
        os.environ["QDML_SCENARIO_BUDGET_S"] = budget

    import bench

    out_dir = os.path.join("results", "scenario_scaling")
    os.makedirs(out_dir, exist_ok=True)
    table = os.path.join(out_dir, "routing_table.json")
    jsonl = os.path.join(out_dir, "scenario_scaling.jsonl")
    if os.path.exists(table):
        os.remove(table)  # the committed table must be THIS run's selections
    os.environ["QDML_SCENARIO_TABLE"] = table

    rc = bench.run_scenario_scaling_child(out_path=jsonl)
    if rc != 0:
        print(f"scenario-scaling child failed rc={rc}", file=sys.stderr)
        return rc

    with open(jsonl) as fh:
        record = [json.loads(ln) for ln in fh if ln.strip()][-1]
    points = record["details"]["scenario_scaling"]["points"]

    # the artifact must round-trip the regression gate: self-vs-self is the
    # committed wiring proof (exit 0); later runs gate against THIS file
    from qdml_tpu.telemetry.report import report_main

    report_rc = report_main(
        [
            f"--current={jsonl}",
            f"--baseline={jsonl}",
            f"--out={os.path.join(out_dir, 'report_scenario.md')}",
        ]
    )

    # the two ends of the crossover the race must prove: dense still wins the
    # reference grid, sparse wins the scale-out regime. A point only counts
    # as proven when it was MEASURED (samples_per_sec present): the dispatch
    # field is assigned before timing, so an errored point must fail the
    # proof, not ride through on its pre-timing label.
    def _proven(p, mode):
        return p.get("dispatch") == mode and "samples_per_sec" in p

    all_measured = all("samples_per_sec" in p for p in points)
    dense_at_3 = all(
        _proven(p, "dense") for p in points if p.get("n_scenarios") == 3
    )
    sparse_at_16 = all(
        _proven(p, "sparse") for p in points if p.get("n_scenarios", 0) >= 16
    ) and any(p.get("n_scenarios", 0) >= 16 for p in points)
    headline = {
        "devices": devices,
        "dispatch_per_s": {
            str(p["n_scenarios"]): {
                "dispatch": p.get("dispatch"),
                "capacity": p.get("capacity"),
                "samples_per_sec": p.get("samples_per_sec"),
                "infer_ms": p.get("infer_ms"),
                "agreement": p.get("agreement"),
                "error": p.get("error"),
            }
            for p in points
        },
        "all_points_measured": all_measured,
        "dense_at_3": dense_at_3,
        "sparse_at_16_plus": sparse_at_16,
        "report_exit": report_rc,
        "table": table,
    }
    with open(os.path.join(out_dir, "SCENARIO_SCALING.json"), "w") as fh:
        json.dump(headline, fh, indent=2)
        fh.write("\n")
    print(json.dumps(headline, indent=2))
    return 0 if (report_rc == 0 and dense_at_3 and sparse_at_16 and all_measured) else 1


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
