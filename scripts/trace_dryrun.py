"""Request-tracing dryrun over REAL backend serve processes (ISSUE 15).

The multi-process proof of the phase-decomposition layer (docs/TELEMETRY.md
"request tracing"): spawn 2 genuine ``qdml-tpu serve`` processes (own
interpreters, own JAX runtimes, own warmups, own compile counters), front
them with a :class:`FleetRouter`, drive MMPP loadgen traffic THROUGH the
router with tracing on, and prove the decomposition end to end. Per the
repo's dryrun noise discipline, BEHAVIOR gates are absolute/invariant and
%-threshold rows are judged only against interleaved contemporaneous
windows:

- **phase decomposition through 2 real backends**: every traced window's
  summary carries batch_wait / queue_wait / compute / fetch (backend-side)
  + pick / wire (router-side, NET — exchange minus the backend's own
  reported total, a duration subtraction, never a cross-host clock
  difference), with full coverage (every request sampled);
- **reconciliation**: per-request phase sums against the CLIENT-observed
  wall time — attributed fraction within tolerance, phase sum never above
  the wall (phases partition, they do not double count);
- **kill-failover trace**: a backend SIGKILLed mid-fleet; a traced request
  whose consistent-hash primary was the victim fails over and its trace
  shows the retry attempts as SEPARATE wire spans (first attempt ok=false);
- **overhead-free off-path**: contemporaneous trace-OFF windows through a
  trace_sample=0 router — summaries carry NO trace block, and the final
  per-backend compile deltas are all-zero across the WHOLE matrix (traced
  windows included: tracing never compiles);
- **report round-trip exit 0** with the new phase-decomposition section
  (best traced window vs interleaved contemporaneous traced baseline, 50%%
  threshold on this 2-core harness);
- **zero stranded futures** in every window (always-armed report gate).

Writes ``results/trace_dryrun/``: ``baseline[_tN].jsonl`` (traced),
``traced_tN.jsonl`` / ``off_tN.jsonl``, ``report_traced.md``,
``TRACE_DRYRUN.json``. ``scripts/run_tier1.sh`` stage 2 re-arms the
zero-stranded and zero-compile gates over these artifacts.

Run: ``python scripts/trace_dryrun.py [--n=240] [--rate=150]
[--deadline-ms=500] [--seed=0]``
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from qdml_tpu.utils.platform import force_cpu  # noqa: E402


def _arg(argv, name, default):
    return next((a.split("=", 1)[1] for a in argv if a.startswith(f"--{name}=")), default)


def _free_port() -> int:
    import socket

    with socket.socket() as sk:
        sk.bind(("127.0.0.1", 0))
        return sk.getsockname()[1]


def main(argv: list[str]) -> int:
    n = int(_arg(argv, "n", "240"))
    rate = float(_arg(argv, "rate", "150"))
    deadline_ms = float(_arg(argv, "deadline-ms", "500"))
    threshold = _arg(argv, "threshold", "50")  # %-rows: identical code, 2-core tail noise
    seed = int(_arg(argv, "seed", "0"))
    trials = int(_arg(argv, "trials", "3"))
    force_cpu(2)

    import asyncio
    from concurrent.futures import Future

    from qdml_tpu.config import (
        DataConfig,
        ExperimentConfig,
        ModelConfig,
        ServeConfig,
        TrainConfig,
    )
    from qdml_tpu.fleet import FleetRouter, route_async, spawn_backend
    from qdml_tpu.serve import ServeClient, make_request_samples, run_loadgen_socket
    from qdml_tpu.telemetry import run_manifest
    from qdml_tpu.telemetry.report import report_main
    from qdml_tpu.telemetry.tracing import TraceContext
    from qdml_tpu.train.hdce import train_hdce
    from qdml_tpu.train.qsc import train_classifier
    from qdml_tpu.utils.metrics import MetricsLogger

    out_dir = os.path.join("results", "trace_dryrun")
    os.makedirs(out_dir, exist_ok=True)
    scratch = tempfile.mkdtemp(prefix="trace_")

    cfg = ExperimentConfig(
        name="trace_dryrun",
        data=DataConfig(n_ant=16, n_sub=8, n_beam=4, data_len=64),
        model=ModelConfig(features=8),
        train=TrainConfig(batch_size=16, n_epochs=8, workdir=scratch, probe_every=0),
        serve=ServeConfig(
            max_batch=16, buckets=(4, 16), max_wait_ms=2.0, max_queue=64,
            batching="bucket", dedup_ttl_s=10.0, conn_timeout_s=5.0,
            supervise=True, arrival="bursty",
        ),
    )
    workdir = os.path.join(scratch, f"Pn_{cfg.data.pilot_num}", cfg.name)
    print("training fleet models (8-epoch HDCE + 8-epoch SC) ...", flush=True)
    tlog = MetricsLogger(os.path.join(scratch, "train.jsonl"), echo=False,
                         manifest=run_manifest(cfg))
    try:
        train_hdce(cfg, logger=tlog, workdir=workdir)
        train_classifier(cfg, quantum=False, logger=tlog, workdir=workdir)
    finally:
        tlog.close()
    samples = make_request_samples(cfg, n)

    backend_overrides = [
        "--name=trace_dryrun",
        "--data.n_ant=16", "--data.n_sub=8", "--data.n_beam=4",
        "--data.data_len=64", "--model.features=8", "--train.batch_size=16",
        f"--train.workdir={scratch}",
        "--serve.max_batch=16", "--serve.buckets=(4,16)",
        "--serve.max_wait_ms=2.0", "--serve.max_queue=64",
        "--serve.batching=bucket", "--serve.dedup_ttl_s=10.0",
        "--serve.conn_timeout_s=5.0", "--serve.supervise=true",
        # backends sample at 0: the ROUTER's trace bit (forwarded "trace":
        # true) is what turns tracing on per request — one knob, one tier,
        # and the off-windows prove the same processes untraced
        "--serve.trace_sample=0.0",
    ]
    ports = [_free_port(), _free_port()]  # fixed: a respawned backend reuses
    # its address, so the router re-admits the same table entry

    def spawn(i: int):
        print(f"spawning backend {i} on :{ports[i]} ...", flush=True)
        b = spawn_backend(backend_overrides, port=ports[i])
        print(json.dumps({"backend": i, "port": b.port, "host_id": b.host_id,
                          "compiles_after_warmup": b.banner[
                              "compile_cache_after_warmup"]}), flush=True)
        return b

    backends = [spawn(0), spawn(1)]

    def make_front(trace_sample: float):
        router = FleetRouter(
            [("127.0.0.1", p) for p in ports],
            balance="hash", timeout_s=2.0, retries=0,
            eject_failures=2, eject_s=0.5, readmit_probes=1,
            poll_interval_s=0.25, failover=2, seed=seed,
            dedup_ttl_s=120.0, trace_sample=trace_sample,
        ).start()
        aloop = asyncio.new_event_loop()
        t = threading.Thread(target=aloop.run_forever, daemon=True)
        t.start()
        ready: Future = Future()
        task = asyncio.run_coroutine_threadsafe(
            route_async(router, "127.0.0.1", 0, ready,
                        conn_timeout_s=5.0, max_line_bytes=1 << 20),
            aloop,
        )
        port = ready.result(timeout=30.0)
        return router, ("127.0.0.1", port), (task, aloop, t)

    router_on, front_on, h_on = make_front(1.0)
    router_off, front_off, h_off = make_front(0.0)
    print(json.dumps({"front_traced": front_on[1], "front_off": front_off[1]}),
          flush=True)

    window_seq = [0]

    def serve_window(tag: str, front) -> tuple[dict, str]:
        path = os.path.join(out_dir, f"{tag}.jsonl")
        logger = MetricsLogger(path, echo=False, manifest=run_manifest(cfg))
        # one seed per WINDOW: loadgen ids are lg{seed}-{i}; a reused id
        # would re-attach to the router dedup from an earlier trial and turn
        # the window into a cache-hit measurement (fleet dryrun lesson)
        window_seq[0] += 1
        try:
            summary = run_loadgen_socket(
                cfg, front, rate=rate, n=n, seed=seed + 1000 * window_seq[0],
                deadline_ms=deadline_ms, logger=logger, clients=8,
                x=samples["x"],
            )
        finally:
            logger.close()
        return summary, path

    def _p99(s):
        return ((s["latency_ms"] or {}).get("p99_ms")) or float("inf")

    def backend_poll(port: int, verb: str = "metrics") -> dict | None:
        try:
            with ServeClient("127.0.0.1", port, timeout_s=5.0, retries=1) as c:
                rep = c.metrics() if verb == "metrics" else c.health()
                return rep.get(verb)
        except Exception:  # lint: disable=broad-except(a dead backend is an expected poll outcome mid-failover; the caller records None)
            return None

    headline: dict = {
        "n": n, "rate": rate, "deadline_ms": deadline_ms, "seed": seed,
        "report_threshold_pct": float(threshold),
        "note": (
            "2-process wiring proof on the 2-core harness: behavior gates "
            "(stranded futures, per-backend compile deltas, coverage, "
            "reconciliation bounds, failover wire spans) are absolute/"
            "invariant; %-threshold phase/latency rows compare identical "
            "code across interleaved contemporaneous traced windows at 50% "
            "(real hardware re-runs arm the default 10%). Wire spans are "
            "router-measured NET durations; no cross-host clock is ever "
            "differenced."
        ),
        "backends": {b.host_id: {"port": b.port} for b in backends},
        "classes": {},
    }
    all_pass = True

    def finish_class(kind: str, checks: dict, ok: bool) -> None:
        nonlocal all_pass
        checks["ok"] = ok
        headline["classes"][kind] = checks
        all_pass = all_pass and ok
        print(json.dumps({kind: {"ok": ok}}), flush=True)

    def trace_block(s: dict) -> dict:
        return s.get("trace") or {}

    def phases_of(s: dict) -> dict:
        return s.get("phases") or {}

    # ------------- interleaved windows: traced baseline / traced / off -------
    base_summary = base_path = None
    cur_summary = cur_path = None
    off_summary = None
    off_rows = []
    traced_rows = []
    for trial in range(trials):
        sb, pb = serve_window(f"baseline_t{trial}" if trial else "baseline",
                              front_on)
        if trial:  # keep the canonical baseline.jsonl name for CI re-reads
            pass
        if base_summary is None or _p99(sb) < _p99(base_summary):
            base_summary, base_path = sb, pb
        st, pt = serve_window(f"traced_t{trial}", front_on)
        traced_rows.append({
            "trial": trial,
            "stranded_futures": st["stranded_futures"],
            "p99_ms": (st["latency_ms"] or {}).get("p99_ms"),
            "trace": {k: trace_block(st).get(k) for k in
                      ("sampled", "fraction", "reconciliation")},
        })
        if cur_summary is None or _p99(st) < _p99(cur_summary):
            cur_summary, cur_path = st, pt
        so, _po = serve_window(f"off_t{trial}", front_off)
        off_rows.append({
            "trial": trial,
            "stranded_futures": so["stranded_futures"],
            "trace": trace_block(so) or None,
            "phases": phases_of(so) or None,
        })
        if off_summary is None or _p99(so) < _p99(off_summary):
            off_summary = so
    # CI reads baseline.jsonl: make it the BEST baseline trial's file
    canonical = os.path.join(out_dir, "baseline.jsonl")
    if base_path != canonical:
        with open(base_path) as src, open(canonical, "w") as dst:
            dst.write(src.read())

    # ------------- class: decomposition + coverage + reconciliation ----------
    ph = phases_of(cur_summary)
    tb = trace_block(cur_summary)
    rec = tb.get("reconciliation") or {}
    have_all_phases = all(
        ph.get(p, {}).get("n") for p in
        ("batch_wait", "queue_wait", "compute", "fetch", "wire", "pick")
    )
    attributed = rec.get("attributed_fraction")
    server_phases_in_metrics = all(
        (row or {}).get("phases")
        for row in (cur_summary.get("server_metrics") or {}).get(
            "per_backend", {}
        ).values()
    )
    finish_class("decomposition", {
        "stranded_futures": max(t["stranded_futures"] for t in traced_rows),
        "phases": {k: {kk: v[kk] for kk in ("n", "mean_ms", "p99_ms")
                       if kk in v} for k, v in ph.items()},
        "coverage": {k: tb.get(k) for k in ("sampled", "completed", "fraction")},
        "reconciliation": rec,
        "per_backend_phases_in_poll": server_phases_in_metrics,
        "traced_trials": traced_rows,
    }, (
        max(t["stranded_futures"] for t in traced_rows) == 0
        and have_all_phases
        and tb.get("fraction") == 1.0
        and attributed is not None
        # phases PARTITION the wall: they attribute a majority of it and
        # never exceed it (1.02 covers per-span rounding at 3 decimals)
        and 0.5 <= attributed <= 1.02
        and server_phases_in_metrics
    ))

    # ------------- class: trace-off windows are trace-free -------------------
    finish_class("trace_off", {
        "off_trials": off_rows,
    }, all(
        t["stranded_futures"] == 0 and t["trace"] is None and t["phases"] is None
        for t in off_rows
    ))

    # ------------- class: kill-failover trace --------------------------------
    # rids whose consistent-hash primary IS the victim, computed BEFORE the
    # kill — the failed wire span only exists while the dead host is still
    # admitted (the health poll ejects it within ~2 poll periods)
    victim_rids, k = [], 0
    while len(victim_rids) < 8:
        rid = f"pin-{seed}-{k}"
        if router_on._candidates(rid)[0].port == ports[1]:
            victim_rids.append(rid)
        k += 1
    backends[1].kill()
    failover_tr = None
    attempts = None
    for rid in victim_rids:
        with ServeClient(front_on[0], front_on[1], timeout_s=10.0,
                         retries=1, seed=seed) as c:
            rep = c.request(samples["x"][0], rid=rid)
        tr = TraceContext.from_wire(rep.get("trace"))
        if rep.get("ok") and tr is not None:
            atts = ((tr.detail or {}).get("router") or {}).get("attempts") or []
            if len(atts) >= 2 and atts[0].get("ok") is False:
                failover_tr, attempts = tr, atts
                break
    wire_spans = (
        [d for nm, d in failover_tr.phases if nm == "wire"]
        if failover_tr is not None else []
    )
    # respawn the victim on its port; the router re-admits the slot
    backends[1] = spawn(1)
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline and len(router_on.live_backends()) < 2:
        router_on.poll_once()
        time.sleep(0.1)
    finish_class("kill_failover_trace", {
        "wire_spans_ms": [round(w * 1e3, 3) for w in wire_spans],
        "attempts": attempts,
        "failover_retries": (
            None if failover_tr is None
            else ((failover_tr.detail or {}).get("router") or {})
            .get("failover_retries")
        ),
        "backends_live_after_respawn": len(router_on.live_backends()),
    }, (
        failover_tr is not None
        and len(wire_spans) >= 2
        and attempts[0]["ok"] is False and attempts[-1]["ok"] is True
        and len(router_on.live_backends()) == 2
    ))

    # post-respawn traced window: the recovered fleet still decomposes
    s_rec, _p_rec = serve_window(f"traced_t{trials}", front_on)
    finish_class("post_respawn", {
        "stranded_futures": s_rec["stranded_futures"],
        "coverage": trace_block(s_rec).get("fraction"),
        "slo": s_rec["slo"],
    }, (
        s_rec["stranded_futures"] == 0
        and trace_block(s_rec).get("fraction") == 1.0
    ))

    # ------------- report round-trip with the phase section ------------------
    report_md = os.path.join(out_dir, "report_traced.md")
    rc = report_main(
        [f"--current={cur_path}", f"--baseline={canonical}",
         f"--threshold={threshold}", f"--out={report_md}"]
    )
    with open(report_md) as fh:
        md = fh.read()
    finish_class("report_round_trip", {
        "exit": rc,
        "has_phase_section": "serving phase decomposition" in md,
        "has_coverage_fact": "trace coverage" in md,
        "has_clock_skew_rule": "never differenced" in md,
        "current": cur_path,
        "baseline": canonical,
    }, (
        rc == 0
        and "serving phase decomposition" in md
        and "trace coverage" in md
        and "never differenced" in md
    ))

    # ------------- per-backend compile gate (absolute, always-armed) ---------
    compile_gate = {}
    for b in backends:
        m = backend_poll(b.port)
        compile_gate[b.host_id] = (
            None if m is None else m.get("compile_cache_after_warmup")
        )
    headline["compile_cache_per_backend"] = compile_gate
    compiles_ok = all(
        isinstance(v, dict) and all(c == 0 for c in v.values())
        for v in compile_gate.values()
    ) and len(compile_gate) == 2
    finish_class("request_path_compiles", {"per_backend": compile_gate},
                 compiles_ok)

    # ------------- teardown + headline ---------------------------------------
    for task, aloop, t in (h_on, h_off):
        task.cancel()
        aloop.call_soon_threadsafe(aloop.stop)
        t.join(timeout=10.0)
    router_on.stop()
    router_off.stop()
    for b in backends:
        b.terminate()
    headline["all_pass"] = all_pass
    with open(os.path.join(out_dir, "TRACE_DRYRUN.json"), "w") as fh:
        json.dump(headline, fh, indent=2)
    print(json.dumps({"all_pass": all_pass}))
    return 0 if all_pass else 1


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
