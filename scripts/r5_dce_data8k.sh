#!/bin/bash
# Reduced-protocol decomposition, DATA arm: 30 epochs x 8k samples/cell.
#
# results/dce/epochs60/ measured the EPOCHS arm (60 ep x 4k/cell) of the
# round-4 protocol reduction and found the below-MMSE tail closes and the
# hierarchy gain widens. This is the complementary arm — same 2x compute
# budget spent on data instead of epochs (30 ep x 8k/cell; steps/epoch
# doubles, total steps equal to the epochs arm) — completing the 2x2:
#   30ep x 4k  (results/dce/)        | 60ep x 4k (results/dce/epochs60/)
#   30ep x 8k  (results/dce/data8k/) | 100ep x 20k = full protocol (TPU)
# If doubling DATA also closes the tail, the two axes trade off; if not,
# the shortfall is specifically training length — sharpening finding 1.
#
# Fresh training (no checkpoints at this data volume); resume-capable.
set -e
cd /root/repo
S=${1:-}
if [ -n "$S" ]; then
  WD=runs/science_cpu_d8k_s$S
  SEEDS="--train.seed=$S --data.seed=$((2026 + S))"
  OUT=results/dce/data8k/seed$S
else
  WD=runs/science_cpu_d8k
  SEEDS=""
  OUT=results/dce/data8k
fi
RED="--data.data_len=8000 --train.n_epochs=30"
for cmd in train-hdce train-sc train-dce; do
  echo "=== $cmd (8k/cell, 30 epochs, seed=${S:-default}) ==="
  python -m qdml_tpu.cli $cmd $RED $SEEDS --train.workdir=$WD --train.resume=true
done
python -m qdml_tpu.cli eval --data.data_len=8000 --train.workdir=$WD \
    --eval.results_dir=$OUT
cp $WD/Pn_128/*/eval.metrics.jsonl $OUT/ 2>/dev/null || true
if [ ! -f $OUT/PROTOCOL.md ]; then
  cat > $OUT/PROTOCOL.md <<'EOF'
# Protocol: 8k samples/cell (2x the reduced runs), 30 epochs

The DATA arm of the reduced-protocol decomposition
(`scripts/r5_dce_data8k.sh`): same total training steps as the epochs arm
(`../epochs60/`, 60 ep x 4k/cell), budget spent on data volume instead.
EOF
fi
echo "DCE DATA8K DONE (seed=${S:-default})"
