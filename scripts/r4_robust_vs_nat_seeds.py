"""Multi-seed robust-vs-QuantumNAT under state-level hardware noise.

VERDICT r3 ask #4: the round-3 "input conditioning and QuantumNAT compose
rather than substitute" claim rested on ONE trained model per cell —
immediately after round 3 itself proved that single-seed deltas of this
size do not replicate (seed_spread.md). This script evaluates the
robust-preset and QuantumNAT classifiers at 3 training seeds each
(seed 0 = the original round-3 pair; seeds 2/3 trained by the same
protocol: 30 epochs, eval on the COMMON seed-2026 test stream) and writes
per-seed rows plus min/mean/max spreads, so the README keeps the claim
only at whatever grain survives.

Reuses the round-3 eval protocol and artifact writer verbatim
(r3_noise_robustness: depolarizing grid over 32-trajectory Pauli-twirl
sims, shared test stream, qsc_best checkpoints) — across-seed differences
measure training variance only, and the table format cannot drift from
the other noise studies'.

Output: results/noise_robustness/robust_vs_nat/seeds/ (the round-3
single-seed artifacts in the parent dir stay untouched).

Usage: python scripts/r4_robust_vs_nat_seeds.py [out_dir]
"""

import json
import os
import statistics
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from qdml_tpu.utils.platform import honor_platform_env

honor_platform_env()

import jax

from qdml_tpu.config import ExperimentConfig
from qdml_tpu.data.channels import ChannelGeometry
from qdml_tpu.models.qsc import QSCP128
from qdml_tpu.train.checkpoint import reconcile_quantum_cfg, restore_checkpoint

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from r3_noise_robustness import (  # noqa: E402
    N_TRAJ,
    P_GRID,
    SNRS,
    TEST_N,
    accuracy,
    common_test_batches,
    write_results,
)

# seed 0 = the original round-3 pair; 2/3 = the seed-study extensions.
# robust_nat is the COMBINATION the compose claim implies (robust preset
# + QuantumNAT sigma=0.05 — the sigma-ensemble's protected group) — round
# 3 never actually trained it; all three of its seeds are round-4 runs.
SEEDS = (0, 2, 3)
MODELS = {
    "robust": {0: "runs/nr_robust/Pn_128/robust_qsc", "t": "runs/nr_robust_s{s}/Pn_128/robust_qsc"},
    "quantumnat": {0: "runs/nr_nat/Pn_128/default", "t": "runs/nr_nat_s{s}/Pn_128/default"},
    "robust_nat": {
        0: "runs/nr_robustnat_s0/Pn_128/robust_qsc",
        "t": "runs/nr_robustnat_s{s}/Pn_128/robust_qsc",
    },
}


def main() -> None:
    out_dir = (
        sys.argv[1] if len(sys.argv) > 1 else "results/noise_robustness/robust_vs_nat/seeds"
    )
    cfg = ExperimentConfig()
    geom = ChannelGeometry.from_config(cfg.data)
    batches = common_test_batches(cfg, geom)

    out = {
        "p_grid": list(P_GRID),
        "n_trajectories": N_TRAJ,
        "test_n": TEST_N,
        "seeds": list(SEEDS),
        "curves": {},
    }
    for label, dirs in MODELS.items():
        for s in SEEDS:
            wd = dirs[0] if s == 0 else dirs["t"].format(s=s)
            vars_, meta = restore_checkpoint(wd, "qsc_best")
            mcfg = reconcile_quantum_cfg(cfg, meta)
            for snr in SNRS:
                accs = []
                for p in P_GRID:
                    model = QSCP128(
                        n_qubits=mcfg.quantum.n_qubits,
                        n_layers=mcfg.quantum.n_layers,
                        n_classes=mcfg.quantum.n_classes,
                        input_norm=mcfg.quantum.input_norm,
                        backend="tensor",
                        depolarizing_p=float(p),
                        n_trajectories=N_TRAJ,
                    )
                    accs.append(
                        round(accuracy(model, vars_, batches[snr], jax.random.PRNGKey(17)), 4)
                    )
                out["curves"][f"{label}_s{s}_snr{snr:g}"] = accs
                print(f"{label} seed {s} @ SNR {snr:g}: {accs}", flush=True)

    # spreads per (model, snr, p) across seeds
    out["spread"] = {}
    for label in MODELS:
        for snr in SNRS:
            rows = [out["curves"][f"{label}_s{s}_snr{snr:g}"] for s in SEEDS]
            out["spread"][f"{label}_snr{snr:g}"] = {
                "min": [round(min(c), 4) for c in zip(*rows)],
                "mean": [round(statistics.mean(c), 4) for c in zip(*rows)],
                "max": [round(max(c), 4) for c in zip(*rows)],
            }

    write_results(out_dir, out, "model seed SNR")
    # append the across-seed spread rows to the shared-format table
    spread_lines = []
    for key, sp in out["spread"].items():
        spread_lines.append(
            f"| {key} mean (min-max) | "
            + " | ".join(
                f"{m:.3f} ({lo:.2f}-{hi:.2f})"
                for m, lo, hi in zip(sp["mean"], sp["min"], sp["max"])
            )
            + " |"
        )
    with open(os.path.join(out_dir, "results_table.md"), "a") as fh:
        fh.write("\n" + "\n".join(spread_lines) + "\n")
    print("\n".join(spread_lines))
    # write_results dumped out (incl. spread) to results.json already
    with open(os.path.join(out_dir, "results.json")) as fh:
        assert "spread" in json.load(fh)


if __name__ == "__main__":
    main()
