#!/bin/bash
# HDCE estimation-curve variance (extends the round-3 SC/robust-QSC spread
# in scripts/r3_multiseed.sh to the NMSE headline): retrain the HDCE
# trunks+head at 3 seeds (40 epochs — past the first LR halving, enough for
# a variance estimate at a fraction of the 100-epoch cost, same shortening
# rationale as the 30-epoch classifier spread), then sweep each against the
# COMMON seed-2026 test stream with the
# COMMON committed science classifiers, so across-seed differences measure
# HDCE training variance only — not classifier variance (measured
# separately, results/robust/) and not test resampling noise.
#
# Needs the TPU chip (scan-fused steps; CPU is ~3 orders slower).
set -e
cd /root/repo

for s in 1 2 3; do
  WD=runs/ms_hdce_s$s
  python -m qdml_tpu.cli train-hdce --train.seed=$s --data.seed=$((2026 + s)) \
      --train.n_epochs=40 --train.scan_steps=16 \
      --train.workdir=$WD --train.resume=true > runs/ms_hdce_s$s.log 2>&1
  # common classifiers: across-seed deltas isolate the estimator
  for t in sc_best sc_best.meta.json qsc_best qsc_best.meta.json; do
    cp -r runs/science/Pn_128/default/$t $WD/Pn_128/default/ 2>/dev/null || true
  done
  python -m qdml_tpu.cli eval --train.workdir=$WD \
      --eval.results_dir=results/hdce_seeds/seed$s > runs/ms_hdce_s$s.eval.log 2>&1
done
echo "HDCE MULTISEED DONE"
