"""Sharded multi-replica serving dryrun on virtual devices (ISSUE 7).

The serve twin of the MULTICHIP mesh dryruns: force a multi-device CPU
backend (``XLA_FLAGS=--xla_force_host_platform_device_count=N`` via
``utils.platform.force_cpu``), drive the mesh-sharded engine with loadgen at
replicas=1 and replicas=2 over the SAME warmed engine family, and feed the
two ``serve_summary`` artifacts through the ``qdml-tpu report`` fleet gate.
Writes ``results/serve_dryrun/``:

- ``loadgen_r{replicas}_t{trial}.jsonl`` — manifest-headed telemetry with
  the fleet-tagged serve_summary records, one file per interleaved trial;
- ``SERVE_DRYRUN.json`` — the headline comparison (rps, p99, SLO attainment,
  zero-compile gate, topology) plus the report-gate exit code;
- ``report_fleet.md`` — the rendered gate (replicas=2 current vs replicas=1
  baseline; the fleet line names both topologies).

Run: ``python scripts/serve_fleet_dryrun.py [--devices=4] [--n=512] [--rate=4000]``
Virtual-device throughput on one CPU host measures dispatch/coalescing
overhead, not ICI scaling — the workload is sized so per-batch device
compute is large enough that replica overlap (one replica in XLA while the
peer does host-side result handling) is visible at all, but the artifact is
primarily the wiring proof (fleet fields flow loadgen -> serve_summary ->
report gate), not a hardware headline. On a real pod the data-sharded
buckets put the batch on ICI-connected chips and the same report gates the
real scaling.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from qdml_tpu.utils.platform import force_cpu  # noqa: E402


def main(argv: list[str]) -> int:
    devices = int(next((a.split("=", 1)[1] for a in argv if a.startswith("--devices=")), 4))
    n = int(next((a.split("=", 1)[1] for a in argv if a.startswith("--n=")), 512))
    rate = float(next((a.split("=", 1)[1] for a in argv if a.startswith("--rate=")), 4000.0))
    force_cpu(devices)

    from qdml_tpu.config import DataConfig, ExperimentConfig, MeshConfig, ModelConfig, ServeConfig, TrainConfig
    from qdml_tpu.parallel.mesh import serve_mesh
    from qdml_tpu.serve import ServeEngine, run_loadgen
    from qdml_tpu.telemetry import run_manifest
    from qdml_tpu.telemetry.report import report_main
    from qdml_tpu.train.hdce import init_hdce_state
    from qdml_tpu.train.qsc import init_sc_state
    from qdml_tpu.utils.metrics import MetricsLogger

    out_dir = os.path.join("results", "serve_dryrun")
    os.makedirs(out_dir, exist_ok=True)

    # Heavy enough per-batch device compute (the full-width trunk stack on a
    # 16x8 pilot image) that a batch's XLA execution dominates its Python
    # result handling — the regime where replica overlap can show at all on
    # one host; tiny toy models are pure GIL contention.
    cfg = ExperimentConfig(
        name="serve_fleet_dryrun",
        data=DataConfig(n_ant=32, n_sub=16, n_beam=8, data_len=64),
        model=ModelConfig(features=32),
        train=TrainConfig(batch_size=16, n_epochs=1),
        mesh=MeshConfig(data_axis=devices, model_axis=1, fed_axis=1),
        serve=ServeConfig(max_batch=32, buckets=(8, 16, 32), max_wait_ms=2.0,
                          max_queue=512, batching="bucket"),  # the committed
        # baselines were measured under bucket coalescing; a regenerated
        # artifact must not silently flip admission policy via the auto table
    )
    mesh = serve_mesh(cfg)
    _, hdce_state = init_hdce_state(cfg, 4)
    hdce_vars = {"params": hdce_state.params, "batch_stats": hdce_state.batch_stats}
    _, sc_state = init_sc_state(cfg, quantum=False, steps_per_epoch=4)
    clf_vars = {"params": sc_state.params}

    trials = int(next((a.split("=", 1)[1] for a in argv if a.startswith("--trials=")), 3))
    headline: dict = {
        "devices": devices,
        "mesh": None,
        "n": n,
        "target_rate": rate,
        "trials": trials,
        "note": (
            "interleaved best-of-N trials: one contended CPU host swings "
            "per-run rps by ~10%, so each setting's best run approximates "
            "its uncontended capability (all trials recorded)"
        ),
        "runs": {},
    }
    paths = {}
    best: dict = {}
    trial_rps: dict = {1: [], 2: []}
    # interleave the replica settings across trials: host contention drifts
    # over minutes, and blocked A-A-A-B-B-B ordering would hand whichever
    # setting ran in the quiet window a fake win
    for trial in range(trials):
        for replicas in (1, 2):
            # fresh engine per run: each run's warmup/compile gate and
            # metrics window stand alone (the executables hit the
            # persistent compile cache, so repeat warmups are cheap)
            engine = ServeEngine(cfg, hdce_vars, clf_vars, mesh=mesh)
            path = os.path.join(out_dir, f"loadgen_r{replicas}_t{trial}.jsonl")
            logger = MetricsLogger(path, echo=False, manifest=run_manifest(cfg))
            try:
                summary = run_loadgen(
                    cfg, engine, rate=rate, n=n, deadline_ms=2000.0,
                    logger=logger, replicas=replicas,
                )
            finally:
                logger.close()
            trial_rps[replicas].append(summary["rps"])
            if replicas not in best or (summary["rps"] or 0) > (best[replicas][0]["rps"] or 0):
                best[replicas] = (summary, path)
    for replicas in (1, 2):
        summary, path = best[replicas]
        headline["mesh"] = summary["mesh"]
        headline["runs"][f"replicas={replicas}"] = {
            "rps": summary["rps"],
            "rps_all_trials": trial_rps[replicas],
            "rps_per_replica": summary.get("rps_per_replica"),
            "offered_rps": summary["offered_rps"],
            "p50_ms": (summary["latency_ms"] or {}).get("p50_ms"),
            "p99_ms": (summary["latency_ms"] or {}).get("p99_ms"),
            "slo": summary["slo"],
            "completed": summary["completed"],
            "n_shed": summary["n_shed"],
            "compile_cache_after_warmup": summary["compile_cache_after_warmup"],
            "bucket_sharding": summary["bucket_sharding"],
        }
        paths[replicas] = path
        print(f"replicas={replicas}: best rps={summary['rps']} (trials {trial_rps[replicas]}) "
              f"p99={(summary['latency_ms'] or {}).get('p99_ms')}ms "
              f"slo={summary['slo']} compiles={summary['compile_cache_after_warmup']}")

    # the fleet gate consumes the records: replicas=2 current vs replicas=1
    # baseline (same platform -> armed; the fleet line names both topologies)
    report_md = os.path.join(out_dir, "report_fleet.md")
    rc = report_main(
        [f"--current={paths[2]}", f"--baseline={paths[1]}", f"--out={report_md}"]
    )
    headline["report_gate"] = {"exit_code": rc, "markdown": report_md}
    with open(os.path.join(out_dir, "SERVE_DRYRUN.json"), "w") as fh:
        json.dump(headline, fh, indent=2)
    print(json.dumps(headline, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
