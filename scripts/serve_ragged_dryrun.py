"""Bucket-vs-ragged continuous batching dryrun on virtual devices (ISSUE 12).

The serving twin of the scenario/qubit crossover artifacts: force the
8-virtual-device CPU backend (``utils.platform.force_cpu``), drive the SAME
warmed engine family through loadgen in both batching modes — ``bucket``
(pad-to-power-of-two + coalesce to bucket edges) and ``ragged`` (traced
valid-count executables + continuous admission) — under the bursty-MMPP and
diurnal arrival processes at two offered-load levels, interleaved best-of-N
trials, and feed each condition's artifacts through the ``qdml-tpu report``
goodput/padding-waste/p99 gates. Writes ``results/serve_ragged/``:

- ``loadgen_{mode}_{process}_r{rate}_t{trial}.jsonl`` — manifest-headed
  telemetry, one file per trial;
- ``RAGGED_DRYRUN.json`` — the headline comparison per condition (p99,
  goodput, padding waste, sheds, zero-compile gate) + report exit codes;
- ``report_{process}_r{rate}.md`` — the rendered gate (ragged current vs
  bucket baseline).

It also warms ONE ``serve.batching=auto`` engine first, which runs the
bucket-vs-ragged race per capacity tier and persists the measured winners to
``results/autotune/serve_batching.json`` — the committed table production
warmups read instead of re-timing.

Config choices that make the comparison honest rather than rigged:

- both modes run the IDENTICAL config; the bucket path's coalescing window
  (``max_wait_ms=10``) is sized the way an SLO-aware bucket deployment
  sizes it — well under the offered deadline (16 ms), leaving service-time
  margin — because the window IS that mode's fill mechanism, and the ragged
  mode's point is not needing one;
- the tier ladder is the full power-of-two ladder, so a small continuous
  dispatch lands in a small tier — continuous admission is NOT allowed to
  win latency by burning padding (the padding-waste gate checks exactly
  this);
- deadlines are offered (SLO serving) and goodput counts USEFUL rows —
  completed within deadline (the serving-literature definition) — so the
  coalescing window's hold converts into measurable goodput loss: a row the
  bucket path delivers after its deadline is throughput, not goodput.

Run: ``python scripts/serve_ragged_dryrun.py [--n=384] [--trials=3]
[--rates=80,400] [--deadline-ms=16] [--max-wait-ms=10]``
Virtual-device timings measure dispatch/coalescing behavior, not ICI — the
per-dispatch cost is nearly flat in batch size on this harness (the
launch-bound regime real accelerators live in), which is exactly the regime
where coalescing windows pay pure latency for fill the ragged path gets for
free. On a real pod the same artifacts re-run and the same gates arm on TPU
numbers.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from qdml_tpu.utils.platform import force_cpu  # noqa: E402


def _arg(argv: list[str], name: str, default: str) -> str:
    return next((a.split("=", 1)[1] for a in argv if a.startswith(f"--{name}=")), default)


def main(argv: list[str]) -> int:
    devices = int(_arg(argv, "devices", "8"))
    n = int(_arg(argv, "n", "384"))
    trials = int(_arg(argv, "trials", "3"))
    rates = [float(r) for r in _arg(argv, "rates", "80,400").split(",")]
    deadline_ms = float(_arg(argv, "deadline-ms", "16"))
    max_wait_ms = float(_arg(argv, "max-wait-ms", "10"))
    force_cpu(devices)

    import dataclasses

    from qdml_tpu.config import (
        DataConfig,
        ExperimentConfig,
        MeshConfig,
        ModelConfig,
        ServeConfig,
        TrainConfig,
    )
    from qdml_tpu.parallel.mesh import serve_mesh
    from qdml_tpu.serve import ServeEngine, run_loadgen
    from qdml_tpu.telemetry import run_manifest
    from qdml_tpu.telemetry.report import report_main
    from qdml_tpu.train.hdce import init_hdce_state
    from qdml_tpu.train.qsc import init_sc_state
    from qdml_tpu.utils.metrics import MetricsLogger

    out_dir = os.path.join("results", "serve_ragged")
    os.makedirs(out_dir, exist_ok=True)

    def cfg_for(batching: str) -> ExperimentConfig:
        # Model sized so per-dispatch service time (~5-15ms here) sits in the
        # launch-bound regime real accelerators serve this pipeline in — the
        # regime where the bucket path's coalescing window is a comparable
        # (not negligible) share of the latency budget. The fleet dryrun's
        # deliberately heavy model measures replica overlap; this one
        # measures admission policy.
        return ExperimentConfig(
            name="serve_ragged_dryrun",
            data=DataConfig(n_ant=16, n_sub=8, n_beam=4, data_len=64),
            model=ModelConfig(features=8),
            train=TrainConfig(batch_size=16, n_epochs=1),
            mesh=MeshConfig(data_axis=devices, model_axis=1, fed_axis=1),
            serve=ServeConfig(
                max_batch=32,
                buckets=(1, 2, 4, 8, 16, 32),
                max_wait_ms=max_wait_ms,
                max_queue=512,
                batching=batching,
            ),
        )

    cfg = cfg_for("bucket")
    mesh = serve_mesh(cfg)
    _, hdce_state = init_hdce_state(cfg, 4)
    hdce_vars = {"params": hdce_state.params, "batch_stats": hdce_state.batch_stats}
    _, sc_state = init_sc_state(cfg, quantum=False, steps_per_epoch=4)
    clf_vars = {"params": sc_state.params}

    # 1) the measured race: one auto-mode warmup persists the per-capacity
    # bucket-vs-ragged winners (results/autotune/serve_batching.json) — the
    # committed table production auto warmups read instead of re-timing
    auto_engine = ServeEngine(
        cfg_for("auto"), hdce_vars, clf_vars, mesh=serve_mesh(cfg_for("auto"))
    )
    auto_warm = auto_engine.warmup()
    race = {
        tier: {
            "best": entry.get("best_infer"),
            "candidates": entry.get("candidates"),
        }
        for tier, entry in auto_warm["batching"]["race"].items()
    }
    print("auto race:", json.dumps(race, indent=2))

    headline: dict = {
        "devices": devices,
        "n": n,
        "trials": trials,
        "deadline_ms": deadline_ms,
        "max_wait_ms": cfg.serve.max_wait_ms,
        "buckets": list(cfg.serve.buckets),
        "auto_race": race,
        "note": (
            "interleaved best-of-N trials per (mode, process, rate): one "
            "contended CPU host swings per-run numbers, so each setting's "
            "best-goodput run approximates its uncontended capability (all "
            "trials recorded); per-dispatch cost is ~flat in batch size on "
            "this harness (launch-bound), so the bucket path's coalescing "
            "window is pure latency tax — the regime real accelerators "
            "live in"
        ),
        "conditions": {},
    }

    conditions = [(proc, rate) for proc in ("bursty", "diurnal") for rate in rates]
    all_pass = True
    for proc, rate in conditions:
        best: dict = {}
        trial_stats: dict = {"bucket": [], "ragged": []}
        for trial in range(trials):
            for mode in ("bucket", "ragged"):
                # fresh engine per run: each run's warmup/compile gate and
                # metrics window stand alone (repeat warmups hit the
                # persistent compile cache)
                mcfg = dataclasses.replace(
                    cfg_for(mode),
                    serve=dataclasses.replace(cfg_for(mode).serve, arrival=proc),
                )
                engine = ServeEngine(mcfg, hdce_vars, clf_vars, mesh=mesh)
                path = os.path.join(
                    out_dir, f"loadgen_{mode}_{proc}_r{int(rate)}_t{trial}.jsonl"
                )
                logger = MetricsLogger(path, echo=False, manifest=run_manifest(mcfg))
                try:
                    summary = run_loadgen(
                        mcfg, engine, rate=rate, n=n, deadline_ms=deadline_ms,
                        logger=logger, process=proc,
                    )
                finally:
                    logger.close()
                stat = {
                    "trial": trial,
                    "goodput_rps": summary["goodput_rps"],
                    "p99_ms": (summary["latency_ms"] or {}).get("p99_ms"),
                    "p50_ms": (summary["latency_ms"] or {}).get("p50_ms"),
                    "padding_waste": summary["padding_waste"],
                    "n_shed": summary["n_shed"],
                    "slo": summary["slo"],
                    "compile_cache_after_warmup": summary["compile_cache_after_warmup"],
                }
                trial_stats[mode].append(stat)
                if mode not in best or (summary["goodput_rps"] or 0) > (
                    best[mode][0]["goodput_rps"] or 0
                ):
                    best[mode] = (summary, path, stat)
        key = f"{proc}_r{int(rate)}"
        report_md = os.path.join(out_dir, f"report_{key}.md")
        rc = report_main(
            [
                f"--current={best['ragged'][1]}",
                f"--baseline={best['bucket'][1]}",
                f"--out={report_md}",
            ]
        )
        all_pass = all_pass and rc == 0
        b, r = best["bucket"][2], best["ragged"][2]
        headline["conditions"][key] = {
            "process": proc,
            "offered_rate": rate,
            "bucket": {**b, "trials": trial_stats["bucket"]},
            "ragged": {**r, "trials": trial_stats["ragged"]},
            "p99_speedup": (
                round(b["p99_ms"] / r["p99_ms"], 3)
                if b["p99_ms"] and r["p99_ms"]
                else None
            ),
            "goodput_gain": (
                round(r["goodput_rps"] / b["goodput_rps"], 3)
                if b["goodput_rps"] and r["goodput_rps"]
                else None
            ),
            "report_gate": {"exit_code": rc, "markdown": report_md},
        }
        print(
            f"{key}: bucket p99={b['p99_ms']}ms goodput={b['goodput_rps']} "
            f"shed={b['n_shed']} | ragged p99={r['p99_ms']}ms "
            f"goodput={r['goodput_rps']} shed={r['n_shed']} | gate rc={rc}"
        )

    headline["report_gates_all_pass"] = all_pass
    with open(os.path.join(out_dir, "RAGGED_DRYRUN.json"), "w") as fh:
        json.dump(headline, fh, indent=2)
    print(json.dumps({k: v for k, v in headline.items() if k != "conditions"}, indent=2))
    return 0 if all_pass else 1


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
