#!/bin/bash
# Full-protocol seed-2 replicate of the DCE-vs-HDCE control (VERDICT r4
# ask #5): the reduced-protocol ordering (results/dce/seed2/, 30 ep x
# 4k/cell) replicated at the exact reference protocol (100 ep x 20k/cell,
# Runner...py:20-38) so the README's hierarchy-gain claim can graduate to a
# measured full-protocol number with spread. Training data draws from an
# independent generator stream (data.seed), evaluation stays on the COMMON
# default test stream — the repo's standing seed discipline. The quantum
# classifier is not retrained (the gap under measurement is DCE-vs-HDCE;
# eval degrades gracefully without a QSC checkpoint, Test.py:81-86
# semantics). On-chip only: pass scan_steps=16 (a ~4x CPU loss otherwise).
set -e
cd /root/repo
WD=runs/science_s2
SEEDS="--train.seed=2 --data.seed=2028"
for cmd in train-hdce train-sc train-dce; do
  echo "=== seed2 full $cmd ==="
  python -m qdml_tpu.cli $cmd $SEEDS --train.workdir=$WD --train.resume=true \
      --train.scan_steps=16
done
python -m qdml_tpu.cli eval --train.workdir=$WD --eval.results_dir=results/dce/seed2
cp $WD/Pn_128/*/eval.metrics.jsonl results/dce/seed2/ 2>/dev/null || true
echo "protocol: full reference (100 ep x 20k/cell), on-chip, $(date -u +%F)" \
    > results/dce/seed2/PROTOCOL_STAMP.txt
echo "DCE SEED2 FULL DONE"
