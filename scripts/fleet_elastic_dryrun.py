"""Elastic fleet lifecycle dryrun over REAL backend serve processes (ISSUE 17).

The multi-process proof of the spawn/warm/admit/drain/retire state machine
(docs/FLEET.md "elastic fleet"): boot a 2-backend fleet of genuine
``qdml-tpu serve`` processes behind a :class:`FleetRouter` + asyncio front
door (with a :class:`BackendLifecycle` attached, so the ``{"op": "fleet"}``
scaling form is armed), drive MMPP ("bursty") loadgen traffic through it,
and prove the four elastic scenarios the tier claims. Per the repo's dryrun
noise discipline, BEHAVIOR gates are absolute/invariant and latency %-rows
are judged only against interleaved contemporaneous windows:

- **scale-up under traffic**: a standby is verified warm (``health.warm``
  + ZERO request-path compile counters over the live verbs) and admitted
  mid-window; zero stranded futures, the admitted backend's compile delta
  stays zero under the traffic it then serves, and the consistent-hash
  audit shows BOUNDED key movement — every moved key moved TO the new
  host, surviving assignments untouched;
- **drain-then-retire under traffic**: the lifecycle-owned backend drains
  (typed ``draining`` state, off the ring, in-flights complete) and exits
  mid-window; zero stranded futures, and a dedup'd retry of an id the
  victim served BEFORE retirement is answered AFTER it — identical reply,
  router dedup hit, zero new dispatches fleet-wide — with the ring audit
  showing assignments restored bit-exactly;
- **kill-during-admission**: a standby killed between spawn and
  verification is quarantined (never admitted); the serving fleet is
  unaffected (zero stranded, membership unchanged);
- **planner-target convergence**: ``plan --emit-target`` over this
  harness's own traced baseline window emits ``backends_needed`` + its
  ``assumptions_sha``; a :class:`FleetAutoscaler` pinned to that target
  converges the fleet one admission/retirement per tick, every decision an
  emitted ``fleet_scale_event`` carrying the sha — and the report
  round-trip over the converged fleet's windows exits 0.

Writes ``results/fleet_elastic/``: ``baseline[_tN].jsonl``,
``{class}_fault.jsonl``, ``{class}_recovery_tN.jsonl`` /
``{class}_base_tN.jsonl``, ``report_{class}.md``, ``fleet_target.json``,
``fleet_scale_events.jsonl``, ``FLEET_ELASTIC.json``.

Run: ``python scripts/fleet_elastic_dryrun.py [--n=240] [--rate=300]
[--deadline-ms=500] [--seed=0]``
"""

from __future__ import annotations

import json
import os
import socket
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from qdml_tpu.utils.platform import force_cpu  # noqa: E402


def _arg(argv, name, default):
    return next((a.split("=", 1)[1] for a in argv if a.startswith(f"--{name}=")), default)


def _free_port() -> int:
    with socket.socket() as sk:
        sk.bind(("127.0.0.1", 0))
        return sk.getsockname()[1]


def main(argv: list[str]) -> int:
    n = int(_arg(argv, "n", "240"))
    rate = float(_arg(argv, "rate", "300"))
    deadline_ms = float(_arg(argv, "deadline-ms", "500"))
    threshold = _arg(argv, "threshold", "50")  # %-rows: identical code, 2-core tail noise
    seed = int(_arg(argv, "seed", "0"))
    trials = int(_arg(argv, "trials", "3"))
    force_cpu(2)

    import asyncio
    from concurrent.futures import Future

    from qdml_tpu.config import (
        DataConfig,
        ExperimentConfig,
        ModelConfig,
        ServeConfig,
        TrainConfig,
    )
    from qdml_tpu.control.fleet_scale import FleetAutoscaler, load_planner_target
    from qdml_tpu.fleet import FleetRouter, route_async, spawn_backend
    from qdml_tpu.fleet.lifecycle import BackendLifecycle
    from qdml_tpu.serve import ServeClient, make_request_samples, run_loadgen_socket
    from qdml_tpu.telemetry import run_manifest
    from qdml_tpu.telemetry.capacity import plan_main
    from qdml_tpu.telemetry.report import report_main
    from qdml_tpu.train.hdce import train_hdce
    from qdml_tpu.train.qsc import train_classifier
    from qdml_tpu.utils.metrics import MetricsLogger

    out_dir = os.path.join("results", "fleet_elastic")
    os.makedirs(out_dir, exist_ok=True)
    scratch = tempfile.mkdtemp(prefix="fleet_elastic_")

    cfg = ExperimentConfig(
        name="fleet_elastic_dryrun",
        data=DataConfig(n_ant=16, n_sub=8, n_beam=4, data_len=64),
        model=ModelConfig(features=8),
        train=TrainConfig(batch_size=16, n_epochs=8, workdir=scratch, probe_every=0),
        serve=ServeConfig(
            max_batch=16, buckets=(4, 16), max_wait_ms=2.0, max_queue=64,
            batching="bucket", dedup_ttl_s=10.0, conn_timeout_s=5.0,
            supervise=True,
            arrival="bursty",  # the elastic scenarios run under MMPP traffic
        ),
    )
    import dataclasses

    workdir = os.path.join(scratch, f"Pn_{cfg.data.pilot_num}", cfg.name)
    print("training fleet models (8-epoch HDCE + 8-epoch SC) ...", flush=True)
    tlog = MetricsLogger(os.path.join(scratch, "train.jsonl"), echo=False,
                         manifest=run_manifest(cfg))
    try:
        train_hdce(cfg, logger=tlog, workdir=workdir)
        sc_cfg = dataclasses.replace(
            cfg, train=dataclasses.replace(cfg.train, n_epochs=8)
        )
        train_classifier(sc_cfg, quantum=False, logger=tlog, workdir=workdir)
    finally:
        tlog.close()
    samples = make_request_samples(cfg, n)

    backend_overrides = [
        "--name=fleet_elastic_dryrun",
        "--data.n_ant=16", "--data.n_sub=8", "--data.n_beam=4",
        "--data.data_len=64", "--model.features=8", "--train.batch_size=16",
        f"--train.workdir={scratch}",
        "--serve.max_batch=16", "--serve.buckets=(4,16)",
        "--serve.max_wait_ms=2.0", "--serve.max_queue=64",
        "--serve.batching=bucket", "--serve.dedup_ttl_s=10.0",
        "--serve.conn_timeout_s=5.0", "--serve.supervise=true",
    ]
    boot_ports = [_free_port(), _free_port()]

    def spawn_boot(i: int):
        print(f"spawning boot backend {i} on :{boot_ports[i]} ...", flush=True)
        b = spawn_backend(backend_overrides, port=boot_ports[i])
        print(json.dumps({"backend": i, "port": b.port, "host_id": b.host_id,
                          "compiles_after_warmup": b.banner[
                              "compile_cache_after_warmup"]}), flush=True)
        return b

    boot = [spawn_boot(0), spawn_boot(1)]
    router = FleetRouter(
        [("127.0.0.1", p) for p in boot_ports],
        balance="hash", timeout_s=2.0, retries=0,
        eject_failures=2, eject_s=0.5, readmit_probes=1,
        poll_interval_s=0.2, failover=2, seed=seed,
        # the drain-spanning dedup pin retries its id AFTER a full fault
        # window + drain-then-retire: the TTL must outlive that
        dedup_ttl_s=300.0,
        # every request traced: the planner consumes this harness's OWN
        # baseline window (plan --emit-target needs phase decomposition)
        trace_sample=1.0,
    ).start()

    # standbys PRE-SPAWNED outside the traffic windows: provisioning a real
    # qdml-tpu serve process (interpreter + JAX + warmup) is tens of seconds
    # of boring cold-start; the events that must be safe UNDER traffic are
    # verification + ring splice (admission) and drain + exit (retirement),
    # and those run mid-window through the lifecycle below
    prepared: list = []

    def spawn_fn(overrides, port=0, host="127.0.0.1", log_path=None,
                 timeout_s=600.0):
        if prepared:
            return prepared.pop(0)
        return spawn_backend(list(overrides), port=port, host=host,
                             log_path=log_path, timeout_s=timeout_s)

    lifecycle = BackendLifecycle(
        router, spawn_overrides=backend_overrides, drain_wait_s=30.0,
        log_dir=scratch, spawn_fn=spawn_fn,
    )
    esink = MetricsLogger(os.path.join(out_dir, "fleet_scale_events.jsonl"),
                          echo=False, manifest=run_manifest(cfg))

    aloop = asyncio.new_event_loop()
    tloop = threading.Thread(target=aloop.run_forever, daemon=True)
    tloop.start()
    ready: Future = Future()
    front_task = asyncio.run_coroutine_threadsafe(
        route_async(router, "127.0.0.1", 0, ready,
                    conn_timeout_s=5.0, max_line_bytes=1 << 20,
                    lifecycle=lifecycle),
        aloop,
    )
    front = ("127.0.0.1", ready.result(timeout=30.0))
    print(json.dumps({"router_front": front[1], "elastic": True}), flush=True)

    window_seq = [0]

    def serve_window(tag: str, during=None):
        side_err: list = []
        side = None
        if during is not None:
            def _side():
                try:
                    during()
                except Exception as e:  # lint: disable=broad-except(the injection side thread must report its failure into the headline, not die silently and fake a passing chaos run)
                    side_err.append(f"{type(e).__name__}: {e}")
            side = threading.Thread(target=_side, daemon=True)
            side.start()
        path = os.path.join(out_dir, f"{tag}.jsonl")
        logger = MetricsLogger(path, echo=False, manifest=run_manifest(cfg))
        # one seed per WINDOW: a reused loadgen id would re-attach to the
        # router's fleet-wide dedup from an earlier trial and measure cache
        # hits, not serving (caught by the backend completed-counter audit)
        window_seq[0] += 1
        try:
            summary = run_loadgen_socket(
                cfg, front, rate=rate, n=n, seed=seed + 1000 * window_seq[0],
                deadline_ms=deadline_ms, logger=logger, clients=8,
                x=samples["x"],
            )
        finally:
            logger.close()
        if side is not None:
            side.join(timeout=120.0)
        if side_err:
            summary["injection_error"] = side_err[0]
        return summary, path

    def _p99(s):
        return ((s["latency_ms"] or {}).get("p99_ms")) or float("inf")

    def backend_poll(port: int, verb: str = "metrics") -> dict | None:
        try:
            with ServeClient("127.0.0.1", port, timeout_s=5.0, retries=1) as c:
                rep = c.metrics() if verb == "metrics" else c.health()
                return rep.get(verb)
        except Exception:  # lint: disable=broad-except(a dead backend is an expected poll outcome mid-chaos; the caller records None)
            return None

    def live_ports() -> list:
        return [b.port for b in router.backends]

    def per_port_completed() -> dict:
        out = {}
        for p in live_ports():
            m = backend_poll(p)
            out[p] = None if m is None else int(m.get("completed") or 0)
        return out

    def _rid_for_primary(port: int) -> str:
        """A request id whose consistent-hash primary is the given backend
        (the retirement-spanning pin must target the victim's id space)."""
        k = 0
        while True:
            rid = f"pin-{seed}-{k}"
            if router._candidates(rid)[0].port == port:
                return rid
            k += 1

    def dedup_retry_pin(rid: str, rep1: dict) -> dict:
        """QUIET-phase fleet-wide dedup pin: retry an already-served id —
        identical reply, a router dedup hit, ZERO new dispatches on any
        live backend (per-port counters bitwise unchanged)."""
        before = per_port_completed()
        hits0 = router.dedup.hits
        with ServeClient(front[0], front[1], timeout_s=10.0, retries=1,
                         backoff_s=0.05, seed=seed) as client:
            rep2 = client.request(samples["x"][0], rid=rid)
        after = per_port_completed()
        ok = (
            rep1.get("ok") is True and rep2.get("ok") is True
            and rep1.get("h") == rep2.get("h")
            and rep2.get("pred") == rep1.get("pred")
            and router.dedup.hits == hits0 + 1
            and all(after[p] == before[p] for p in after
                    if before.get(p) is not None and after[p] is not None)
        )
        return {"ok": ok, "rid": rid, "dedup_hits": router.dedup.hits,
                "completed_before": before, "completed_after": after}

    #: the ring audit's probe ids — NEVER offered as traffic (the audit
    #: reads routing assignments, it must not seed dedup entries)
    audit_keys = [f"ring-audit-{i}" for i in range(3000)]

    def ring_assignment() -> dict:
        return {k: router._candidates(k)[0].addr for k in audit_keys}

    headline: dict = {
        "n": n, "rate": rate, "deadline_ms": deadline_ms, "seed": seed,
        "arrival_process": "bursty",
        "report_threshold_pct": float(threshold),
        "note": (
            "elastic-lifecycle wiring proof on the 2-core harness: behavior "
            "gates (stranded futures, warm-verified admission, per-backend "
            "compile deltas, bounded ring movement, retirement-spanning "
            "dedup, quarantine-on-kill, planner convergence) are absolute/"
            "invariant; %-threshold latency rows compare identical code "
            "across interleaved contemporaneous windows at 50% (real "
            "hardware re-runs arm the default 10%)"
        ),
        "boot_backends": {b.host_id: {"port": b.port} for b in boot},
        "classes": {},
    }
    all_pass = True

    def finish_class(kind: str, checks: dict, ok: bool) -> None:
        nonlocal all_pass
        checks["ok"] = ok
        headline["classes"][kind] = checks
        all_pass = all_pass and ok
        print(json.dumps({kind: {"ok": ok}}), flush=True)

    def recovery_report(kind: str) -> dict:
        """Post-scenario steady state: best-of-N recovery vs interleaved
        contemporaneous local baselines + the report round-trip."""
        rec_summary = rec_path = lb_summary = lb_path = None
        rec_trials = []
        for trial in range(trials):
            s, p = serve_window(f"{kind}_recovery_t{trial}")
            rec_trials.append({
                "trial": trial,
                "stranded_futures": s["stranded_futures"],
                "give_ups": s["give_ups"],
                "hard_give_ups": s["give_ups"] - s["deadline_give_ups"],
                "p99_ms": (s["latency_ms"] or {}).get("p99_ms"),
                "slo": s["slo"],
            })
            if rec_summary is None or _p99(s) < _p99(rec_summary):
                rec_summary, rec_path = s, p
            sb, pb = serve_window(f"{kind}_base_t{trial}")
            if lb_summary is None or _p99(sb) < _p99(lb_summary):
                lb_summary, lb_path = sb, pb
        report_md = os.path.join(out_dir, f"report_{kind}.md")
        rc = report_main(
            [f"--current={rec_path}", f"--baseline={lb_path}",
             f"--threshold={threshold}", f"--out={report_md}"]
        )
        rec_att = (rec_summary["slo"] or {}).get("attainment")
        lb_att = (lb_summary["slo"] or {}).get("attainment")
        return {
            "recovery_trials": rec_trials,
            "stranded_futures_recovery": max(
                t["stranded_futures"] for t in rec_trials
            ),
            "hard_give_ups_recovery": max(
                t["hard_give_ups"] for t in rec_trials
            ),
            "slo_recovery": rec_summary["slo"],
            "slo_local_baseline": lb_summary["slo"],
            "slo_reattained": rec_att is not None
            and (lb_att is None or rec_att >= lb_att - 0.05),
            "report_exit": rc,
        }

    # ---------------- baseline: 2-backend fleet, best-of-N -------------------
    base_summary = base_path = None
    for trial in range(trials):
        s, p = serve_window(f"baseline_t{trial}" if trial else "baseline")
        if base_summary is None or _p99(s) < _p99(base_summary):
            base_summary, base_path = s, p
    both_served = all(
        (v or {}).get("completed") for v in
        (base_summary.get("server_metrics") or {}).get("per_backend", {}).values()
    ) and len((base_summary.get("server_metrics") or {}).get("per_backend", {})) == 2
    served_total = sum(v or 0 for v in per_port_completed().values())
    finish_class("baseline", {
        "completed": base_summary["completed"],
        "stranded_futures": base_summary["stranded_futures"],
        "slo": base_summary["slo"],
        "both_backends_served": both_served,
        "backend_completed_total": served_total,
        "offered_total": trials * n,
        "path": base_path,
    }, (
        base_summary["stranded_futures"] == 0 and both_served
        and served_total >= trials * n - n // 10
    ))

    # ---------------- the fleet verb over the wire ----------------------------
    with ServeClient(front[0], front[1], timeout_s=60.0) as c:
        verb_status = c.fleet()
        verb_noop = c.fleet(backends=lifecycle.fleet_size())  # converged no-op
    finish_class("fleet_verb", {
        "status_ok": verb_status.get("ok"),
        "elastic": (verb_status.get("fleet") or {}).get("elastic"),
        "backends": (verb_status.get("fleet") or {}).get("backends"),
        "noop_scale_ok": verb_noop.get("ok"),
        "noop_actions": len((verb_noop.get("fleet") or {}).get("actions", [])),
    }, (
        verb_status.get("ok") is True
        and (verb_status.get("fleet") or {}).get("elastic") is True
        and (verb_status.get("fleet") or {}).get("backends") == 2
        and verb_noop.get("ok") is True
        and (verb_noop.get("fleet") or {}).get("actions") == []
    ))

    # ---------------- scale-up under traffic ----------------------------------
    print("provisioning standby for scale-up ...", flush=True)
    prepared.append(spawn_backend(backend_overrides, port=0,
                                  log_path=os.path.join(scratch, "standby1.log")))
    ring_before = ring_assignment()
    up_box: dict = {}

    def inject_scale_up():
        time.sleep((n // 3) / rate)  # mid-window: verify + ring splice
        up_box["rec"] = lifecycle.scale_up()

    s_up, _p = serve_window("scale_up_fault", during=inject_scale_up)
    up_rec = up_box.get("rec") or {"ok": False, "error": "injection never ran"}
    ring_after_up = ring_assignment()
    moved = [k for k in audit_keys if ring_after_up[k] != ring_before[k]]
    new_addr = up_rec.get("addr")
    moved_to_new = all(ring_after_up[k] == new_addr for k in moved)
    moved_frac = len(moved) / len(audit_keys)
    new_port = int(new_addr.rsplit(":", 1)[1]) if new_addr else None
    new_compiles = (backend_poll(new_port) or {}).get(
        "compile_cache_after_warmup"
    ) if new_port else None
    up_checks = {
        "stranded_futures_fault": s_up["stranded_futures"],
        "admission": up_rec,
        "fleet_after": lifecycle.fleet_size(),
        "ring_moved_fraction": round(moved_frac, 4),
        "ring_moved_only_to_new_host": moved_to_new,
        "new_backend_compiles_after_traffic": new_compiles,
        "injection_error": s_up.get("injection_error"),
    }
    up_checks.update(recovery_report("scale_up"))
    finish_class("scale_up", up_checks, (
        s_up["stranded_futures"] == 0
        and up_rec.get("ok") is True and up_rec.get("stage") == "admitted"
        and (up_rec.get("verified") or {}).get("warm") is True
        and lifecycle.fleet_size() == 3
        and moved and moved_to_new and 0.05 < moved_frac < 0.60
        and isinstance(new_compiles, dict)
        and all(v == 0 for v in new_compiles.values())
        and s_up.get("injection_error") is None
        and up_checks["stranded_futures_recovery"] == 0
        and up_checks["hard_give_ups_recovery"] == 0
        and up_checks["slo_reattained"] and up_checks["report_exit"] == 0
    ))

    # ---------------- drain-then-retire under traffic -------------------------
    # pin an id whose primary IS the retiring backend, served BEFORE the
    # retirement: the post-retirement retry must be answered by the router's
    # fleet-wide dedup, not re-dispatched
    pin_rid = _rid_for_primary(new_port)
    with ServeClient(front[0], front[1], timeout_s=10.0, retries=1,
                     seed=seed) as _c:
        pin_rep1 = _c.request(samples["x"][0], rid=pin_rid)
    down_box: dict = {}

    def inject_scale_down():
        time.sleep((n // 3) / rate)  # mid-window: drain + exit
        down_box["rec"] = lifecycle.scale_down()

    s_down, _p = serve_window("drain_retire_fault", during=inject_scale_down)
    down_rec = down_box.get("rec") or {"ok": False, "error": "injection never ran"}
    ring_after_down = ring_assignment()
    pin = dedup_retry_pin(pin_rid, pin_rep1)
    down_checks = {
        "stranded_futures_fault": s_down["stranded_futures"],
        "retirement": down_rec,
        "fleet_after": lifecycle.fleet_size(),
        "ring_restored_exactly": ring_after_down == ring_before,
        "dedup_across_retirement": pin,
        "injection_error": s_down.get("injection_error"),
    }
    down_checks.update(recovery_report("drain_retire"))
    finish_class("drain_retire", down_checks, (
        s_down["stranded_futures"] == 0
        and down_rec.get("ok") is True and down_rec.get("stage") == "retired"
        and down_rec.get("addr") == new_addr
        and down_rec.get("drained") is True
        and down_rec.get("terminated") is True
        and lifecycle.fleet_size() == 2
        and ring_after_down == ring_before
        and pin["ok"]
        and s_down.get("injection_error") is None
        and down_checks["stranded_futures_recovery"] == 0
        and down_checks["hard_give_ups_recovery"] == 0
        and down_checks["slo_reattained"] and down_checks["report_exit"] == 0
    ))

    # ---------------- kill-during-admission -----------------------------------
    print("provisioning standby for kill-during-admission ...", flush=True)
    standby2 = spawn_backend(backend_overrides, port=0,
                             log_path=os.path.join(scratch, "standby2.log"))
    prepared.append(standby2)

    from qdml_tpu.fleet.lifecycle import verify_warm

    def killing_verify(host, port, timeout_s=10.0):
        standby2.kill()  # SIGKILL between spawn and verification
        return verify_warm(host, port, timeout_s=timeout_s)

    lc_kill = BackendLifecycle(
        router, spawn_overrides=backend_overrides, spawn_fn=spawn_fn,
        verify_fn=killing_verify,
    )
    kill_box: dict = {}

    def inject_kill_admission():
        time.sleep((n // 3) / rate)
        kill_box["rec"] = lc_kill.scale_up()

    s_kill, _p = serve_window("admission_kill_fault", during=inject_kill_admission)
    kill_rec = kill_box.get("rec") or {"ok": False, "error": "injection never ran"}
    kill_addr = kill_rec.get("addr")
    kill_lc_state = (lc_kill.status()["lifecycle"].get(kill_addr) or {}).get("state")
    finish_class("admission_kill", {
        "stranded_futures_fault": s_kill["stranded_futures"],
        "quarantine": kill_rec,
        "lifecycle_state": kill_lc_state,
        "fleet_after": lifecycle.fleet_size(),
        "live_backends": len(router.live_backends()),
        "standby_alive": standby2.alive(),
        "injection_error": s_kill.get("injection_error"),
    }, (
        s_kill["stranded_futures"] == 0
        and kill_rec.get("ok") is False
        and kill_rec.get("stage") == "quarantined"
        and kill_lc_state == "quarantined"
        and lifecycle.fleet_size() == 2
        and len(router.live_backends()) == 2
        and not standby2.alive()
        and s_kill.get("injection_error") is None
    ))

    # ---------------- planner-target convergence ------------------------------
    target_path = os.path.join(out_dir, "fleet_target.json")
    plan_rc = plan_main([
        f"--trace={base_path}", f"--target-rps={rate}",
        f"--p99-ms={deadline_ms}", "--max-backends=3",
        f"--emit-target={target_path}",
    ])
    tgt = None
    scale_events: list = []
    converged = False
    desired = None
    if plan_rc == 0:
        tgt = load_planner_target(target_path)
        desired = max(1, min(3, int(tgt["backends_needed"])))
        # displace the fleet off the target so convergence has work to do
        # (a no-op "convergence" would prove nothing)
        if lifecycle.fleet_size() == desired:
            lifecycle.scale_to(desired + 1 if desired < 3 else desired - 1)
        scaler = FleetAutoscaler(
            lifecycle.scale_to, min_backends=1, max_backends=3,
            cooldown_ticks=0, sink=esink.telemetry,
        )
        scaler.set_planner_target(tgt)
        slo_att = (base_summary["slo"] or {}).get("attainment") or 1.0
        for _ in range(6):
            ev = scaler.observe(
                0.0, lifecycle.fleet_size(), slo_attainment=slo_att
            )
            if ev is not None:
                scale_events.append(ev)
            if lifecycle.fleet_size() == desired:
                converged = True
                break
    plan_checks = {
        "plan_exit": plan_rc,
        "target": tgt,
        "desired_clamped": desired,
        "displaced_then_converged": converged,
        "fleet_after": lifecycle.fleet_size(),
        "scale_events": [
            {k: e.get(k) for k in
             ("direction", "backends", "backends_before", "planner_sha")}
            for e in scale_events
        ],
        "events_carry_planner_sha": bool(scale_events) and all(
            e.get("planner_sha") == (tgt or {}).get("assumptions_sha")
            for e in scale_events
        ),
        "scale_results_ok": all(
            (e.get("result") or {}).get("ok") for e in scale_events
        ),
    }
    plan_checks.update(recovery_report("planner_target"))
    finish_class("planner_target", plan_checks, (
        plan_rc == 0 and tgt is not None
        and isinstance(tgt.get("backends_needed"), int)
        and len(tgt.get("assumptions_sha") or "") == 64
        and converged and lifecycle.fleet_size() == desired
        and len(scale_events) >= 1
        and plan_checks["events_carry_planner_sha"]
        and plan_checks["scale_results_ok"]
        and plan_checks["stranded_futures_recovery"] == 0
        and plan_checks["hard_give_ups_recovery"] == 0
        and plan_checks["slo_reattained"] and plan_checks["report_exit"] == 0
    ))

    # ---------------- per-backend compile gate (absolute, always-armed) ------
    compile_gate = {}
    for b in router.backends:
        m = backend_poll(b.port)
        compile_gate[b.host_id] = None if m is None else m.get(
            "compile_cache_after_warmup"
        )
    headline["compile_cache_per_backend"] = compile_gate
    compiles_ok = bool(compile_gate) and all(
        isinstance(v, dict) and all(c == 0 for c in v.values())
        for v in compile_gate.values()
    )
    finish_class("request_path_compiles", {"per_backend": compile_gate}, compiles_ok)

    headline["lifecycle_status"] = lifecycle.status()

    # ---------------- teardown + headline ------------------------------------
    front_task.cancel()
    aloop.call_soon_threadsafe(aloop.stop)
    tloop.join(timeout=10.0)
    router.stop()
    lifecycle.close()
    lc_kill.close()
    for b in boot:
        b.terminate()
    for p in prepared:
        p.kill()
    esink.close()
    # runtime lock-order witness (QDML_LOCKDEP=1 re-runs gate on zero
    # inversions; disabled runs record the block with enabled=false)
    from qdml_tpu.utils import lockdep
    witness = lockdep.witness_summary()
    headline["lockdep"] = witness
    if witness["enabled"]:
        all_pass = all_pass and witness["inversions"] == 0
    headline["all_pass"] = all_pass
    with open(os.path.join(out_dir, "FLEET_ELASTIC.json"), "w") as fh:
        json.dump(headline, fh, indent=2, default=str)
    print(json.dumps({"all_pass": all_pass}))
    return 0 if all_pass else 1


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
