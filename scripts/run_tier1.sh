#!/usr/bin/env bash
# Tier-1 verify — lint gate + the ROADMAP.md test command, VERBATIM.
#
# Stage 1: graftlint (qdml-tpu lint --baseline; docs/ANALYSIS.md). New static-
# analysis findings fail fast (exit 5) before any test runs — the lint is
# pure AST, no jax, sub-second.
# Stage 2: the ROADMAP.md pytest command, byte-for-byte (this script exists so
# CI and humans run the exact gate the driver runs, including the DOTS_PASSED
# accounting; edit ROADMAP.md first if that line ever needs to change).
cd "$(dirname "$0")/.." || exit 2
python -m qdml_tpu.cli lint --baseline || exit 5
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c); exit $rc
