#!/usr/bin/env bash
# Tier-1 verify — lint gate + resilience report gate + the ROADMAP.md test
# command, VERBATIM.
#
# Stage 1: graftlint (qdml-tpu lint --baseline; docs/ANALYSIS.md). New static-
# analysis findings fail fast (exit 5) before any test runs — the lint is
# pure AST, no jax, sub-second.
# Stage 2: resilience report gate over the committed chaos + fleet-router
# artifacts (docs/RESILIENCE.md, docs/FLEET.md): every committed recovery window is
# fed through `qdml-tpu report --json` and the INVARIANT/absolute rows are
# checked — the ALWAYS-ARMED stranded-futures row plus the
# breaker/overflow/padding absolute-slack gates. The %-threshold latency
# rows are deliberately NOT gated here: they exist for
# best-vs-contemporaneous-baseline comparisons, which the dryrun itself
# runs (scripts/chaos_dryrun.py); CI re-reading an arbitrary committed
# trial against the global baseline would gate host noise, not code.
# Exit 6 on failure.
# Stage 3: the ROADMAP.md pytest command, byte-for-byte (this script exists so
# CI and humans run the exact gate the driver runs, including the DOTS_PASSED
# accounting; edit ROADMAP.md first if that line ever needs to change).
cd "$(dirname "$0")/.." || exit 2
python -m qdml_tpu.cli lint --baseline || exit 5
# Concurrency stage (exit-5 family, docs/ANALYSIS.md "whole-program
# concurrency"): the lint call above already ran the four concurrency rules
# (they ride the same baseline/suppression gate); what remains is the
# artifact discipline — the committed static lock-order graph
# (results/lockgraph/) must byte-match a regenerated one (the documented
# hierarchy is generated, never asserted) and stay cycle-free, and the
# committed QDML_LOCKDEP=1 chaos witness (results/lockdep_dryrun/) must
# certify zero runtime lock-order inversions across injected crash +
# restart + swap.
python -m qdml_tpu.cli lint --baseline --lockgraph-check > /dev/null \
  || { echo "lock graph stale or cyclic: run 'qdml-tpu lint --lockgraph' and commit results/lockgraph/"; exit 5; }
python -c "
import json, sys
g = json.load(open('results/lockgraph/lockgraph.json'))
sys.exit(1 if g.get('cycles') else 0)
" || { echo "committed lock graph contains cycles"; exit 5; }
if [ -f results/lockdep_dryrun/CHAOS_DRYRUN.json ]; then
  python -c "
import json, sys
d = json.load(open('results/lockdep_dryrun/CHAOS_DRYRUN.json'))
w = d.get('lockdep') or {}
ok = (d.get('all_pass') and w.get('enabled') is True
      and w.get('inversions') == 0 and (w.get('locks') or 0) > 0)
sys.exit(0 if ok else 1)
" || { echo "lockdep witness artifact failed (enabled/zero-inversions/all_pass)"; exit 5; }
else
  echo "missing results/lockdep_dryrun/CHAOS_DRYRUN.json (QDML_LOCKDEP=1 chaos witness)"; exit 5
fi
# One parameterized pass over the committed chaos-style artifact sets
# (results/chaos_dryrun, results/fleet_router, results/fleet_elastic —
# docs/RESILIENCE.md, docs/FLEET.md): every recovery window re-arms the
# invariant rows.
for spec in "chaos_dryrun:CHAOS_DRYRUN.json" "fleet_router:FLEET_ROUTER.json" "fleet_elastic:FLEET_ELASTIC.json"; do
  dir="results/${spec%%:*}"; headline="$dir/${spec#*:}"
  [ -d "$dir" ] || continue
  for f in "$dir"/*_recovery_t0.jsonl; do
    # fresh JSON per window: a report crash must FAIL this window, not be
    # silently judged on the previous window's stale gate file
    rm -f /tmp/_t1_invariant.json
    python -m qdml_tpu.cli report --current="$f" \
      --baseline="$dir/baseline.jsonl" \
      --json=/tmp/_t1_invariant.json > /dev/null || true  # rc judged on the JSON rows below
    python -c "
import json, sys
d = json.load(open('/tmp/_t1_invariant.json'))
invariant_kinds = ('resilience', 'breaker', 'dispatch', 'batching')
bad = d.get('stranded_failed') or any(
    g.get('status') == 'regression' and g.get('kind') in invariant_kinds
    for g in d.get('gates', [])
)
sys.exit(1 if bad else 0)
" || { echo "invariant gate failed: $f"; exit 6; }
  done
  python -c "import json, sys; d = json.load(open('$headline')); sys.exit(0 if d.get('all_pass') else 1)" \
    || { echo "committed dryrun is not all_pass: $headline"; exit 6; }
done
# Elastic fleet dryrun (docs/FLEET.md "elastic fleet",
# results/fleet_elastic): beyond the generic invariant pass above, re-check
# the headline's absolute elastic facts — warm-verified admission with
# bounded ring movement (every moved key moved TO the new host, assignments
# restored bit-exactly after retirement), the retirement-spanning dedup pin,
# quarantine on kill-during-admission, planner-target convergence with the
# sealed assumptions sha, and zero per-backend request-path compile deltas.
if [ -f results/fleet_elastic/FLEET_ELASTIC.json ]; then
  python -c "
import json, sys
d = json.load(open('results/fleet_elastic/FLEET_ELASTIC.json'))
c = d.get('classes') or {}
up, down = c.get('scale_up') or {}, c.get('drain_retire') or {}
pt = c.get('planner_target') or {}
zero = lambda m: isinstance(m, dict) and all(v == 0 for v in m.values())
comp = d.get('compile_cache_per_backend') or {}
ok = (d.get('all_pass')
      and up.get('ring_moved_only_to_new_host') is True
      and 0 < (up.get('ring_moved_fraction') or 0) < 0.6
      and down.get('ring_restored_exactly') is True
      and (down.get('dedup_across_retirement') or {}).get('ok') is True
      and (c.get('admission_kill') or {}).get('lifecycle_state') == 'quarantined'
      and len(((pt.get('target') or {}).get('assumptions_sha') or '')) == 64
      and pt.get('events_carry_planner_sha') is True
      and comp and all(zero(v) for v in comp.values()))
sys.exit(0 if ok else 1)
" || { echo "fleet-elastic headline failed (ring/dedup/quarantine/planner/compile)"; exit 6; }
fi
# Trace dryrun (docs/TELEMETRY.md, results/trace_dryrun): re-arm the
# zero-stranded gate over every committed traced window (same invariant-rows
# rule as above — %-threshold phase/latency rows are the dryrun's own
# interleaved-contemporaneous comparison, not CI's), and re-check the
# headline's absolute facts: all_pass, the per-backend ZERO request-path
# compile deltas with tracing on, and the trace-off window's zero deltas.
if [ -d results/trace_dryrun ]; then
  for f in results/trace_dryrun/traced_t*.jsonl; do
    [ -e "$f" ] || continue
    rm -f /tmp/_t1_trace.json
    python -m qdml_tpu.cli report --current="$f" \
      --baseline=results/trace_dryrun/baseline.jsonl \
      --json=/tmp/_t1_trace.json > /dev/null || true  # rc judged on the JSON rows below
    python -c "
import json, sys
d = json.load(open('/tmp/_t1_trace.json'))
invariant_kinds = ('resilience', 'breaker', 'dispatch', 'batching')
bad = d.get('stranded_failed') or any(
    g.get('status') == 'regression' and g.get('kind') in invariant_kinds
    for g in d.get('gates', [])
)
sys.exit(1 if bad else 0)
" || { echo "trace invariant gate failed: $f"; exit 6; }
  done
  python -c "
import json, sys
d = json.load(open('results/trace_dryrun/TRACE_DRYRUN.json'))
zero = lambda m: isinstance(m, dict) and all(v == 0 for v in m.values())
ok = d.get('all_pass') and d.get('compile_cache_per_backend') and all(
    zero(v) for v in d['compile_cache_per_backend'].values()
)
sys.exit(0 if ok else 1)
" || { echo "trace dryrun headline failed (all_pass / zero-compile)"; exit 6; }
fi
# Monitor dryrun (docs/TELEMETRY.md "flight deck", results/monitor_dryrun):
# re-arm the invariant rows PLUS the always-armed monitor gates — the alert
# expectations baked into the committed monitor_summary's expect block (the
# injected-stall segment must have paged, the healthy segments must not)
# and the capacity planner's validation band — then re-check the headline's
# absolute facts (all_pass, health/metrics-only scrape verbs, zero
# per-backend request-path compile deltas) and re-run the planner's
# self-replay validation from scratch over committed windows (exit 0).
if [ -d results/monitor_dryrun ]; then
  rm -f /tmp/_t1_monitor.json
  python -m qdml_tpu.cli report \
    --current=results/monitor_dryrun/recovery_t0.jsonl,results/monitor_dryrun/monitor.jsonl \
    --baseline=results/monitor_dryrun/baseline_t0.jsonl \
    --json=/tmp/_t1_monitor.json > /dev/null || true  # rc judged on the JSON rows below
  python -c "
import json, sys
d = json.load(open('/tmp/_t1_monitor.json'))
invariant_kinds = ('resilience', 'breaker', 'dispatch', 'batching', 'monitor')
bad = d.get('stranded_failed') or d.get('monitor_failed') or any(
    g.get('status') == 'regression' and g.get('kind') in invariant_kinds
    for g in d.get('gates', [])
)
sys.exit(1 if bad else 0)
" || { echo "monitor invariant gate failed"; exit 6; }
  python -c "
import json, sys
d = json.load(open('results/monitor_dryrun/MONITOR_DRYRUN.json'))
c = d.get('classes') or {}
sv = c.get('scrape_verbs_and_compiles') or {}
zero = lambda m: isinstance(m, dict) and all(v == 0 for v in m.values())
comp = sv.get('per_backend_compiles') or {}
ok = (d.get('all_pass') and sv.get('verbs_used') == ['health', 'metrics']
      and comp and all(zero(v) for v in comp.values()))
sys.exit(0 if ok else 1)
" || { echo "monitor dryrun headline failed (all_pass / verbs / zero-compile)"; exit 6; }
  python -m qdml_tpu.cli plan \
    --trace=results/trace_dryrun/traced_t0.jsonl,results/monitor_dryrun/baseline_t0.jsonl,results/monitor_dryrun/recovery_t0.jsonl \
    --validate > /dev/null \
    || { echo "planner self-replay validation failed"; exit 6; }
fi
# Live fleet dryrun (docs/TELEMETRY.md "event spine" + docs/CONTROL.md
# "hands-off loop", results/live_fleet): re-run the report over the
# committed monitor stream with the always-armed event-loss and hands-off
# gates, then re-check the headline's absolute facts — all_pass, the
# events/health/metrics-only scrape discipline, zero per-backend
# request-path compile deltas through the mid-traffic warm admission, a
# zero loss ledger on the event spine, and the burn-alert-correlated
# scale-up (every up decision carries the episode id of the alert that
# drove it — the correlation is a join, not timestamp proximity).
if [ -d results/live_fleet ]; then
  rm -f /tmp/_t1_live.json
  python -m qdml_tpu.cli report \
    --current=results/live_fleet/baseline_t0.jsonl,results/live_fleet/monitor.jsonl \
    --baseline=results/live_fleet/baseline_t0.jsonl \
    --json=/tmp/_t1_live.json > /dev/null || true  # rc judged on the JSON rows below
  python -c "
import json, sys
d = json.load(open('/tmp/_t1_live.json'))
invariant_kinds = ('resilience', 'breaker', 'dispatch', 'batching', 'monitor')
gates = {g.get('metric'): g.get('status') for g in d.get('gates', [])}
bad = (d.get('stranded_failed') or d.get('monitor_failed')
       or gates.get('monitor.event_drops') != 'ok'
       or gates.get('monitor.handsoff') != 'ok'
       or any(g.get('status') == 'regression' and g.get('kind') in invariant_kinds
              for g in d.get('gates', [])))
sys.exit(1 if bad else 0)
" || { echo "live-fleet invariant gate failed (event loss / hands-off)"; exit 6; }
  python -c "
import json, sys
d = json.load(open('results/live_fleet/LIVE_FLEET.json'))
c = d.get('classes') or {}
sv = c.get('scrape_verbs_and_compiles') or {}
spine = c.get('event_spine_zero_loss') or {}
ups = (c.get('handsoff_scale_up') or {}).get('up_decisions') or []
zero = lambda m: isinstance(m, dict) and all(v == 0 for v in m.values())
comp = sv.get('per_backend_compiles') or {}
ok = (d.get('all_pass')
      and sv.get('verbs_used') == ['events', 'health', 'metrics']
      and comp and all(zero(v) for v in comp.values())
      and spine.get('ring_dropped') == 0 and spine.get('cursor_lost') == 0
      and spine.get('give_up') is None
      and ups and all(u.get('burn_alert') and u.get('alert_episode')
                      for u in ups))
sys.exit(0 if ok else 1)
" || { echo "live-fleet headline failed (all_pass / verbs / zero-compile / spine / correlation)"; exit 6; }
fi
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c); exit $rc
