"""Live observability loop dryrun over REAL backend serve processes (ISSUE 18).

The hands-off proof of the event spine + monitor attachment
(docs/CONTROL.md "hands-off loop", docs/TELEMETRY.md "event spine"): boot
a 2-backend fleet of genuine ``qdml-tpu serve`` processes behind a
:class:`FleetRouter` + asyncio front door with a :class:`BackendLifecycle`
attached, pre-spawn a warm standby, and attach a :class:`MonitorScraper`
THROUGH a :class:`MonitorAttachment` at the front door — scraping over the
three sanctioned read verbs (health / metrics / events, audited) and
acting through a SEPARATE ``{"op": "fleet"}`` actuator. Then injure the
fleet and let the loop run itself:

- **burn-alert-driven scale-up**: a SIGSTOP'd backend pages the burn-rate
  alerter AND drops the router's live count below the provisioned
  membership; the attachment's autoscaler ticks see burn + short-handed
  fleet (these ms-latency models fail over faster than instantaneous
  queue depth can ever build, so the live-count deficit is the honest
  corroborating signal) and scale UP — the emitted ``fleet_scale_event``
  carries the ``alert_episode`` id, joining it to the ``monitor_alert``
  BY ID in the committed event stream — and the lifecycle warm-admits the
  prepared standby with ZERO request-path compiles, mid-traffic (a surge
  window, started only AFTER the page so the causality is not a race,
  keeps the survivor under realistic load through the admission), no
  human in the loop;
- **drain on recovery**: the stalled backend resumes, the alert resolves,
  queue depth collapses — the same loop scales back DOWN
  (drain-then-retire) without ever being told to;
- **zero event loss**: the monitor tails the front door's aggregated
  event spine every window with a resumable per-source cursor; the
  committed ``monitor_summary`` carries ``event_drops == 0`` (ring
  evictions + cursor-lapped evictions, both zero) and the report's
  always-armed gate re-arms it forever;
- **quiet segments silent**: no alert fires during the healthy baseline
  (the ``expect`` block makes the report re-check this from the
  committed stream);
- **report round-trip exit 0** with the new monitoring gates (event
  spine loss ledger + hands-off correlation) green.

Writes ``results/live_fleet/``: ``monitor.jsonl`` (the attachment stream,
spine envelopes included), ``baseline_t0/stall_t0/surge_t0/recovery_t0
.jsonl`` (traffic windows), ``report_live_fleet.md``, ``LIVE_FLEET.json``.

Run: ``python scripts/live_fleet_dryrun.py [--n=240] [--rate=60]
[--surge-rate=300] [--deadline-ms=500] [--seed=0]``
"""

from __future__ import annotations

import glob
import json
import os
import socket
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from qdml_tpu.utils.platform import force_cpu  # noqa: E402


def _arg(argv, name, default):
    return next((a.split("=", 1)[1] for a in argv if a.startswith(f"--{name}=")), default)


def _free_port() -> int:
    with socket.socket() as sk:
        sk.bind(("127.0.0.1", 0))
        return sk.getsockname()[1]


class VerbAuditPoller:
    """The monitor's poller, pinned: ONLY the three observability verbs
    exist on this object — a scraper reaching for request/swap/scale/fleet
    would AttributeError into its scrape_error path, and the audit set
    proves what it actually used. Acting happens on a SEPARATE actuator."""

    def __init__(self, inner):
        self._inner = inner
        self.calls: set = set()

    def health(self):
        self.calls.add("health")
        return self._inner.health()

    def metrics(self):
        self.calls.add("metrics")
        return self._inner.metrics()

    def events(self, cursor=None, limit=512):
        self.calls.add("events")
        return self._inner.events(cursor, limit=limit)


def main(argv: list[str]) -> int:
    n = int(_arg(argv, "n", "240"))
    rate = float(_arg(argv, "rate", "60"))
    surge_rate = float(_arg(argv, "surge-rate", "300"))
    deadline_ms = float(_arg(argv, "deadline-ms", "500"))
    threshold = _arg(argv, "threshold", "50")
    seed = int(_arg(argv, "seed", "0"))
    force_cpu(2)

    import asyncio
    import dataclasses
    from concurrent.futures import Future

    from qdml_tpu.config import (
        ControlConfig,
        DataConfig,
        ExperimentConfig,
        ModelConfig,
        ServeConfig,
        TrainConfig,
    )
    from qdml_tpu.control.fleet_scale import FleetAutoscaler
    from qdml_tpu.control.loop import SocketPoller
    from qdml_tpu.fleet import FleetRouter, route_async, spawn_backend
    from qdml_tpu.fleet.lifecycle import BackendLifecycle
    from qdml_tpu.serve import ServeClient, make_request_samples, run_loadgen_socket
    from qdml_tpu.telemetry import run_manifest, set_sink
    from qdml_tpu.telemetry.attach import MonitorAttachment
    from qdml_tpu.telemetry.burnrate import BurnAlerter, BurnRateRule
    from qdml_tpu.telemetry.report import report_main
    from qdml_tpu.telemetry.timeseries import MonitorScraper
    from qdml_tpu.train.hdce import train_hdce
    from qdml_tpu.train.qsc import train_classifier
    from qdml_tpu.utils.metrics import MetricsLogger

    out_dir = os.path.join("results", "live_fleet")
    os.makedirs(out_dir, exist_ok=True)
    for stale in glob.glob(os.path.join(out_dir, "*.jsonl")):
        os.remove(stale)  # telemetry streams APPEND: a prior run's records
        # would smuggle its alerts/decisions into this run's gates
    scratch = tempfile.mkdtemp(prefix="live_fleet_")

    cfg = ExperimentConfig(
        name="live_fleet_dryrun",
        data=DataConfig(n_ant=16, n_sub=8, n_beam=4, data_len=64),
        model=ModelConfig(features=8),
        train=TrainConfig(batch_size=16, n_epochs=8, workdir=scratch, probe_every=0),
        serve=ServeConfig(
            max_batch=16, buckets=(4, 16), max_wait_ms=2.0, max_queue=64,
            batching="bucket", dedup_ttl_s=10.0, conn_timeout_s=5.0,
            supervise=True,
        ),
        control=ControlConfig(min_window=6, autoscale=False),
    )
    workdir = os.path.join(scratch, f"Pn_{cfg.data.pilot_num}", cfg.name)
    print("training fleet models (8-epoch HDCE + 8-epoch SC) ...", flush=True)
    tlog = MetricsLogger(os.path.join(scratch, "train.jsonl"), echo=False,
                         manifest=run_manifest(cfg))
    try:
        train_hdce(cfg, logger=tlog, workdir=workdir)
        sc_cfg = dataclasses.replace(
            cfg, train=dataclasses.replace(cfg.train, n_epochs=8)
        )
        train_classifier(sc_cfg, quantum=False, logger=tlog, workdir=workdir)
    finally:
        tlog.close()
    samples = make_request_samples(cfg, int(n * 2))

    backend_overrides = [
        "--name=live_fleet_dryrun",
        "--data.n_ant=16", "--data.n_sub=8", "--data.n_beam=4",
        "--data.data_len=64", "--model.features=8", "--train.batch_size=16",
        f"--train.workdir={scratch}",
        "--serve.max_batch=16", "--serve.buckets=(4,16)",
        "--serve.max_wait_ms=2.0", "--serve.max_queue=64",
        "--serve.batching=bucket", "--serve.dedup_ttl_s=10.0",
        "--serve.conn_timeout_s=5.0", "--serve.supervise=true",
    ]
    boot_ports = [_free_port(), _free_port()]

    def spawn_boot(i: int):
        print(f"spawning boot backend {i} on :{boot_ports[i]} ...", flush=True)
        b = spawn_backend(backend_overrides, port=boot_ports[i])
        print(json.dumps({"backend": i, "port": b.port, "host_id": b.host_id,
                          "compiles_after_warmup": b.banner[
                              "compile_cache_after_warmup"]}), flush=True)
        return b

    boot = [spawn_boot(0), spawn_boot(1)]
    router = FleetRouter(
        [("127.0.0.1", p) for p in boot_ports],
        balance="hash", timeout_s=1.0, retries=0,
        eject_failures=2, eject_s=0.5, readmit_probes=1,
        poll_interval_s=0.2, failover=2, seed=seed,
        dedup_ttl_s=120.0,
    ).start()

    # the standby is PRE-SPAWNED outside the traffic windows (provisioning
    # is boring cold-start); what must happen hands-off UNDER traffic is
    # the autoscaler's decision + verification + ring splice, and that runs
    # mid-window through the attachment below
    prepared: list = []

    def spawn_fn(overrides, port=0, host="127.0.0.1", log_path=None,
                 timeout_s=600.0):
        if prepared:
            return prepared.pop(0)
        return spawn_backend(list(overrides), port=port, host=host,
                             log_path=log_path, timeout_s=timeout_s)

    lifecycle = BackendLifecycle(
        router, spawn_overrides=backend_overrides, drain_wait_s=30.0,
        log_dir=scratch, spawn_fn=spawn_fn,
    )

    aloop = asyncio.new_event_loop()
    tloop = threading.Thread(target=aloop.run_forever, daemon=True)
    tloop.start()
    ready: Future = Future()
    front_task = asyncio.run_coroutine_threadsafe(
        route_async(router, "127.0.0.1", 0, ready,
                    conn_timeout_s=5.0, max_line_bytes=1 << 20,
                    lifecycle=lifecycle),
        aloop,
    )
    front = ("127.0.0.1", ready.result(timeout=30.0))
    print(json.dumps({"router_front": front[1], "elastic": True}), flush=True)

    print("provisioning warm standby ...", flush=True)
    prepared.append(spawn_backend(backend_overrides, port=0,
                                  log_path=os.path.join(scratch, "standby.log")))

    # -------- attach the live loop (3 read verbs + separate actuator) -----
    mon_path = os.path.join(out_dir, "monitor.jsonl")
    mlog = MetricsLogger(mon_path, echo=False, manifest=run_manifest(cfg))
    # the stack's structured events (router ejections, control scale
    # decisions) reach the monitor stream TWICE on purpose: once through
    # the process-global sink (durable record) and once as tailed
    # ``spine_event`` envelopes (the live-tail proof with correlation keys)
    set_sink(mlog.telemetry)
    audit = VerbAuditPoller(SocketPoller(front[0], front[1], timeout_s=5.0))
    alerter = BurnAlerter.for_run(duration_s=30.0, interval_s=0.4,
                                  slo_target=0.99, threshold=8.0, debounce=2)
    # harness-scaled router rule (same geometry as monitor_dryrun): the
    # fast-ejecting router caps what a short stall can burn, so the pair
    # runs tighter/lower than the production-shaped default
    alerter.rules["router"] = BurnRateRule(
        "router", budget=0.02, fast_s=1.2, slow_s=3.6,
        threshold=3.0, debounce=2,
    )
    scraper = MonitorScraper(audit, sink=mlog.telemetry, interval_s=0.4,
                             alerter=alerter, tail_events=True)
    # the acting path: a SEPARATE poller, fleet verb only — the autoscaler
    # converges membership through the front door exactly like a remote
    # ``qdml-tpu monitor --attach`` would
    actuator = SocketPoller(front[0], front[1], timeout_s=120.0)
    # queue_high sits ABOVE what the 8-client baseline loadgen can ever
    # pile up (in-flight caps queue depth at ~clients) and well BELOW the
    # 32-client surge's overload plateau — the grow signal is the surge
    # hitting a half-fleet, never healthy-traffic jitter
    autoscaler = FleetAutoscaler(
        lambda k: actuator.fleet(backends=k),
        min_backends=2, max_backends=3,
        queue_high=10.0, queue_low=2.0, debounce=2, cooldown_ticks=6,
        sink=mlog.telemetry,
    )
    attachment = MonitorAttachment(scraper, autoscaler, max_reconnects=8)
    stop_mon = threading.Event()
    scraper.mark("baseline_t0")
    mon_thread = threading.Thread(
        target=attachment.run, args=(600.0,), kwargs={"stop": stop_mon},
        daemon=True,
    )
    mon_thread.start()

    window_seq = [0]

    def serve_window(tag: str, n_win: int, w_rate: float, during=None,
                     clients: int = 8):
        side_err: list = []
        side = None
        if during is not None:
            def _side():
                try:
                    during()
                except Exception as e:  # lint: disable=broad-except(the injection side thread must report its failure into the headline, not die silently and fake a passing run)
                    side_err.append(f"{type(e).__name__}: {e}")
            side = threading.Thread(target=_side, daemon=True)
            side.start()
        path = os.path.join(out_dir, f"{tag}.jsonl")
        logger = MetricsLogger(path, echo=False, manifest=run_manifest(cfg))
        window_seq[0] += 1  # fresh loadgen ids per window (dedup discipline)
        try:
            summary = run_loadgen_socket(
                cfg, front, rate=w_rate, n=n_win,
                seed=seed + 1000 * window_seq[0],
                deadline_ms=deadline_ms, logger=logger, clients=clients,
                x=samples["x"],
            )
        finally:
            logger.close()
        if side is not None:
            side.join(timeout=120.0)
        if side_err:
            summary["injection_error"] = side_err[0]
        return summary, path

    def backend_poll(port: int) -> dict | None:
        try:
            with ServeClient("127.0.0.1", port, timeout_s=5.0, retries=1) as c:
                return c.metrics().get("metrics")
        except Exception:  # lint: disable=broad-except(a dead/stalled backend is an expected poll outcome here; the caller records None)
            return None

    headline: dict = {
        "n": n, "rate": rate, "surge_rate": surge_rate,
        "deadline_ms": deadline_ms, "seed": seed,
        "monitor": {"interval_s": scraper.interval_s,
                    "verbs": "health/metrics/events (audited), fleet on a "
                             "separate actuator"},
        "autoscaler": {"min_backends": 2, "max_backends": 3,
                       "queue_high": 10.0, "queue_low": 2.0,
                       "debounce": 2, "cooldown_ticks": 6},
        "boot_backends": {b.host_id: {"port": b.port} for b in boot},
        "classes": {},
    }
    all_pass = True

    def finish_class(kind: str, checks: dict, ok: bool) -> None:
        nonlocal all_pass
        checks["ok"] = ok
        headline["classes"][kind] = checks
        all_pass = all_pass and ok
        print(json.dumps({kind: {"ok": ok}}), flush=True)

    # -------- baseline segment: healthy fleet, quiet loop -----------------
    base_summary, base_path = serve_window("baseline_t0", n, rate)
    time.sleep(1.2)  # stream drains; any late window still carries this mark
    finish_class("baseline", {
        "completed": base_summary["completed"],
        "stranded_futures": base_summary["stranded_futures"],
        "slo": base_summary["slo"],
        "decisions_during_baseline": len(attachment.decisions),
        "path": base_path,
    }, (
        base_summary["stranded_futures"] == 0
        and base_summary["completed"] > 0
        and len(attachment.decisions) == 0
    ))

    # -------- injected stall -> page -> surge -> hands-off scale-up -------
    scraper.mark("stall_t0")
    surge_box: dict = {}

    def inject_stall_then_surge():
        time.sleep(1.0)
        boot[1].stall()  # SIGSTOP: forwards to it time out and fail over
        # wait for the PAGE before offering the surge: the scale-up is
        # driven by burn + the live-count deficit, and holding the surge
        # until the alert burns keeps the decision<->episode correlation
        # causal, not a race
        t_end = time.monotonic() + 10.0
        while time.monotonic() < t_end and not alerter.firing():
            time.sleep(0.1)
        surge_box["paged_before_surge"] = bool(alerter.firing())
        s, p = serve_window("surge_t0", int(n * 2), surge_rate, clients=32)
        surge_box["summary"], surge_box["path"] = s, p
        # hold the stall until the loop has decided (or timeout honestly)
        t_end = time.monotonic() + 10.0
        while time.monotonic() < t_end and not attachment.decisions:
            time.sleep(0.1)
        boot[1].resume()

    stall_summary, stall_path = serve_window(
        "stall_t0", int(n * 2), rate, during=inject_stall_then_surge
    )
    time.sleep(2.0)  # late burn transitions still attribute to stall_t0

    # the loop (not this harness) admitted the standby: wait until it has
    # DECIDED up (the admission itself is synchronous inside the decision).
    # The fleet may already be back at 2 by the time we look — the loop
    # drains on its own once the alert resolves, and a loop fast enough to
    # finish the whole arc before the harness checks is the point, not a
    # failure; the scale-up proof is the admitted scale#N record.
    deadline = time.monotonic() + 30.0
    while (time.monotonic() < deadline
           and not any(d.get("direction") == "up"
                       for d in attachment.decisions)):
        time.sleep(0.2)
    surge_summary = surge_box.get("summary") or {}
    fired = [a for a in scraper.alerts if a.get("state") == "firing"]
    fired_marks = sorted({a.get("mark") for a in fired})
    episodes = {a.get("episode") for a in fired if a.get("episode")}
    ups = [d for d in attachment.decisions if d.get("direction") == "up"]
    up_correlated = [
        d for d in ups
        if d.get("burn_alert") and d.get("alert_episode") in episodes
    ]
    up_results_ok = all(
        isinstance(d.get("result"), dict)
        and all(a.get("stage") == "admitted"
                for a in d["result"].get("actions") or [{}])
        for d in ups
    )
    finish_class("handsoff_scale_up", {
        "fired_marks": fired_marks,
        "episodes": sorted(episodes),
        "paged_before_surge": surge_box.get("paged_before_surge"),
        "up_decisions": [
            {k: d.get(k) for k in ("direction", "backends", "decision",
                                   "burn_alert", "alert_episode")}
            for d in ups
        ],
        "up_results_ok": up_results_ok,
        "fleet_after": lifecycle.fleet_size(),
        "stall_window": {
            "completed": stall_summary["completed"],
            "stranded_futures": stall_summary["stranded_futures"],
        },
        "surge_window": {
            "completed": surge_summary.get("completed"),
            "stranded_futures": surge_summary.get("stranded_futures"),
        },
        "injection_error": stall_summary.get("injection_error"),
    }, (
        "stall_t0" in fired_marks
        and "baseline_t0" not in fired_marks
        and surge_box.get("paged_before_surge") is True
        and len(ups) >= 1 and len(up_correlated) >= 1
        and up_results_ok
        and max((d.get("backends") or 0) for d in ups) == 3
        and lifecycle.fleet_size() in (2, 3)
        and stall_summary["stranded_futures"] == 0
        and surge_summary.get("stranded_futures") == 0
        and stall_summary.get("injection_error") is None
    ))

    # -------- recovery: alert resolves, the loop drains back down ---------
    # router re-admits the resumed backend before the recovery window
    # (wait for the CURRENT membership, however large the loop grew it)
    deadline = time.monotonic() + 30.0
    while (time.monotonic() < deadline
           and len(router.live_backends()) < lifecycle.fleet_size()):
        router.poll_once()
        time.sleep(0.1)
    scraper.mark("recovery_t0")
    rec_summary, rec_path = serve_window("recovery_t0", n, rate)
    # idle drain-down: the attachment keeps ticking; once the alert has
    # resolved and the queue sits under the low watermark the loop retires
    # the extra backend on its own
    deadline = time.monotonic() + 45.0
    while time.monotonic() < deadline and lifecycle.fleet_size() > 2:
        time.sleep(0.3)
    downs = [d for d in attachment.decisions if d.get("direction") == "down"]
    resolved = [a for a in scraper.alerts if a.get("state") == "resolved"]
    finish_class("handsoff_drain", {
        "down_decisions": [
            {k: d.get(k) for k in ("direction", "backends", "decision",
                                   "burn_alert", "alert_episode")}
            for d in downs
        ],
        "alerts_resolved": len(resolved),
        "fleet_after": lifecycle.fleet_size(),
        "recovery_window": {
            "completed": rec_summary["completed"],
            "stranded_futures": rec_summary["stranded_futures"],
        },
    }, (
        len(downs) >= 1
        and all(not d.get("burn_alert") for d in downs)
        and len(resolved) >= 1
        and lifecycle.fleet_size() == 2
        and rec_summary["stranded_futures"] == 0
    ))
    time.sleep(1.2)
    stop_mon.set()
    mon_thread.join(timeout=15.0)

    # -------- event spine: zero loss + by-id join in the tailed stream ----
    spine_ok = (
        scraper.events_seen > 0
        and scraper.event_drops == 0
        and scraper.events_lost == 0
    )
    finish_class("event_spine_zero_loss", {
        "events_seen": scraper.events_seen,
        "ring_dropped": scraper.event_drops,
        "cursor_lost": scraper.events_lost,
        "give_up": attachment.give_up,
        "reattaches": attachment.reattaches,
    }, spine_ok and attachment.give_up is None)

    # -------- scrape discipline: verbs + per-backend compile deltas -------
    verbs = sorted(audit.calls)
    compile_gate = {}
    for b in router.backends:
        m = backend_poll(b.port)
        compile_gate[b.host_id] = None if m is None else m.get(
            "compile_cache_after_warmup")
    compiles_ok = len(compile_gate) == 2 and all(
        isinstance(v, dict) and all(c == 0 for c in v.values())
        for v in compile_gate.values()
    )
    finish_class("scrape_verbs_and_compiles", {
        "verbs_used": verbs,
        "per_backend_compiles": compile_gate,
        "scrape_errors": scraper.scrape_errors,
    }, verbs == ["events", "health", "metrics"] and compiles_ok)

    # -------- summary + report round-trip ---------------------------------
    expect = {"fired": ["stall_t0"], "quiet": ["baseline_t0"],
              "scale_up_correlated": True}
    scraper.finish(extra={"expect": expect,
                          "handsoff": attachment.summary()})
    set_sink(None)
    mlog.close()

    # the committed monitor stream must carry the by-id join: a firing
    # monitor_alert envelope AND a fleet_scale_event envelope tailed off
    # the SPINE (kind=spine_event) sharing one episode id
    alert_eps: set = set()
    scale_eps: set = set()
    with open(mon_path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            obj = json.loads(line)
            if obj.get("kind") != "spine_event":
                continue
            env = obj.get("ev") or {}
            if env.get("kind") == "monitor_alert" and env.get("episode") \
                    and (env.get("data") or {}).get("state") == "firing":
                alert_eps.add(env["episode"])
            if env.get("kind") == "fleet_scale_event" and env.get("episode"):
                scale_eps.add(env["episode"])
    joined = sorted(alert_eps & scale_eps)
    finish_class("spine_correlation", {
        "alert_episodes_on_spine": sorted(alert_eps),
        "scale_episodes_on_spine": sorted(scale_eps),
        "joined_episodes": joined,
    }, len(joined) >= 1)

    # round-trip (repo self-vs-self pattern): committed baseline + monitor
    # stream against the baseline itself must exit 0 WITH the new gates
    # armed — a nonzero loss ledger or an uncorrelated scale-up flips it
    report_md = os.path.join(out_dir, "report_live_fleet.md")
    report_json = os.path.join(out_dir, "report_live_fleet.json")
    rc = report_main([
        f"--current={base_path},{mon_path}", f"--baseline={base_path}",
        f"--threshold={threshold}", f"--out={report_md}",
        f"--json={report_json}",
    ])
    with open(report_md) as fh:
        monitor_lines = [ln.strip() for ln in fh if "alert expectation" in ln
                         or "event spine" in ln or "hands-off loop" in ln]
    with open(report_json) as fh:
        gate_json = json.load(fh)
    gate_rows = {g["metric"]: g["status"] for g in gate_json.get("gates", [])
                 if g.get("kind") == "monitor"}
    finish_class("report_roundtrip", {
        "exit": rc,
        "monitor_gate_lines": monitor_lines,
        "monitor_gate_rows": gate_rows,
    }, (
        rc == 0
        and not gate_json.get("monitor_failed")
        and gate_rows.get("monitor.event_drops") == "ok"
        and gate_rows.get("monitor.handsoff") == "ok"
        and len(monitor_lines) >= 4
    ))

    # -------- teardown + headline ----------------------------------------
    front_task.cancel()
    aloop.call_soon_threadsafe(aloop.stop)
    tloop.join(timeout=10.0)
    router.stop()
    lifecycle.close()
    for b in boot:
        b.terminate()
    for p in prepared:
        p.kill()
    # runtime lock-order witness (QDML_LOCKDEP=1 re-runs gate on zero
    # inversions; disabled runs record the block with enabled=false)
    from qdml_tpu.utils import lockdep
    witness = lockdep.witness_summary()
    headline["lockdep"] = witness
    if witness["enabled"]:
        all_pass = all_pass and witness["inversions"] == 0
    headline["all_pass"] = all_pass
    with open(os.path.join(out_dir, "LIVE_FLEET.json"), "w") as fh:
        json.dump(headline, fh, indent=2, default=str)
    print(json.dumps({"all_pass": all_pass}))
    return 0 if all_pass else 1


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
