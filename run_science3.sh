#!/bin/bash
# Science phase 3: the monolithic-DCE architectural control.
#
# The reference defines DCE_P128 (Estimators_QuantumNAT_onchipQNN.py:40-75)
# but its shipped runner never trains it and Test.py never evaluates it —
# the hierarchical design's gain over the monolithic baseline is asserted,
# not measured. Train DCE under the exact reference protocol (100 epochs,
# bs 256, Adam 1e-3 halved/30, train SNR 10) on the same data grid, then
# sweep ALL estimators in one eval so the DCE curve sits next to
# LS/MMSE/HDCE in one internally-consistent figure.
#
# Training workdirs do not survive rounds (only committed files do), so
# this phase re-trains the full reference protocol into runs/science if the
# checkpoints are absent, then adds DCE. The sweep writes to results/dce/
# (not results/) so the committed round-3 headline artifacts stay exactly
# the runs they were trained from; results/dce/ is its own consistent set.
set -e
cd /root/repo
WD=runs/science
# Unconditional with --train.resume=true: a finished run resumes at
# start_epoch == n_epochs and exits immediately, while a partially-trained
# one (whose *_best already exists) continues — an existence guard on
# *_best would mistake partial for complete.
for cmd in train-hdce train-sc train-qsc; do
  echo "=== $cmd (reference protocol, resume-capable) ==="
  python -m qdml_tpu.cli $cmd --train.workdir=$WD --train.resume=true --train.scan_steps=16
done
python -m qdml_tpu.cli train-dce --train.workdir=$WD --train.resume=true --train.scan_steps=16
python -m qdml_tpu.cli eval --train.workdir=$WD --eval.results_dir=results/dce
# the per-SNR eval rows land in the (gitignored) run dir; copy them next to
# the curves so the committed artifact set carries the JSONL evidence too
cp $WD/Pn_128/*/eval.metrics.jsonl results/dce/ 2>/dev/null || true
echo "SCIENCE PHASE 3 DONE"
