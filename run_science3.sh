#!/bin/bash
# Science phase 3: the monolithic-DCE architectural control.
#
# The reference defines DCE_P128 (Estimators_QuantumNAT_onchipQNN.py:40-75)
# but its shipped runner never trains it and Test.py never evaluates it —
# the hierarchical design's gain over the monolithic baseline is asserted,
# not measured. Train DCE under the exact reference protocol (100 epochs,
# bs 256, Adam 1e-3 halved/30, train SNR 10) on the same data grid, then
# re-run the sweep so results/ carries the DCE curve next to LS/MMSE/HDCE.
set -e
cd /root/repo
python -m qdml_tpu.cli train-dce --train.workdir=runs/science --train.resume=true --train.scan_steps=16
python -m qdml_tpu.cli eval --train.workdir=runs/science --eval.results_dir=results
echo "SCIENCE PHASE 3 DONE"
